/* paddle_tpu C inference API — the reference's C client surface
 * (paddle/fluid/inference/capi_exp/pd_inference_api.h — unverified,
 * SURVEY.md §0/§2.6) over the TPU-native Predictor.
 *
 * Scope: float32 tensors, model loading from a jit.save prefix, input /
 * output handles, Run, per-thread Clone. The implementation embeds the
 * Python runtime (libpython) and drives paddle_tpu.inference — the
 * compiled XLA program does the serving work; this shim is the C ABI.
 *
 * Thread-safety: calls take the GIL; use one PD_Predictor per thread
 * via PD_PredictorClone (clones share the compiled program).
 */
#ifndef PADDLE_TPU_INFER_CAPI_H_
#define PADDLE_TPU_INFER_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Config PD_Config;
typedef struct PD_Predictor PD_Predictor;
typedef struct PD_Tensor PD_Tensor;

/* config ------------------------------------------------------------- */
PD_Config* PD_ConfigCreate(void);
/* prefix of the jit.save artifact (…/model -> model.pdmodel + params) */
void PD_ConfigSetModel(PD_Config* c, const char* prog_prefix,
                       const char* params_file /* may be NULL */);
void PD_ConfigDestroy(PD_Config* c);

/* predictor ---------------------------------------------------------- */
PD_Predictor* PD_PredictorCreate(PD_Config* c);      /* NULL on failure */
PD_Predictor* PD_PredictorClone(PD_Predictor* p);
void PD_PredictorDestroy(PD_Predictor* p);

int PD_PredictorGetInputNum(PD_Predictor* p);
int PD_PredictorGetOutputNum(PD_Predictor* p);       /* valid after Run */
/* returned string is owned by the predictor; valid until Destroy */
const char* PD_PredictorGetInputName(PD_Predictor* p, int i);
const char* PD_PredictorGetOutputName(PD_Predictor* p, int i);

PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor* p, const char* name);
PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor* p, const char* name);

/* tensors ------------------------------------------------------------ */
void PD_TensorReshape(PD_Tensor* t, int ndim, const int64_t* shape);
void PD_TensorCopyFromCpuFloat(PD_Tensor* t, const float* data);
void PD_TensorCopyToCpuFloat(PD_Tensor* t, float* data);
int PD_TensorGetNumDims(PD_Tensor* t);
void PD_TensorGetShape(PD_Tensor* t, int64_t* shape_out);
void PD_TensorDestroy(PD_Tensor* t);                 /* handle only */

/* 0 on success */
int PD_PredictorRun(PD_Predictor* p);

/* last error message ("" when none); owned by the library */
const char* PD_GetLastError(void);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_INFER_CAPI_H_ */
