// paddle_tpu C inference API implementation — embeds the Python runtime
// and drives paddle_tpu.inference (see header for scope/reference notes).
// Build:
//   g++ -O2 -shared -fPIC paddle_tpu_infer_capi.cc \
//       -I$(python -c "import sysconfig;print(sysconfig.get_paths()['include'])") \
//       $(python3-config --embed --ldflags) -o libpaddle_tpu_infer.so
#include "paddle_tpu_infer_capi.h"

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      g_last_error = c != nullptr ? c : "unknown python error";
      Py_DECREF(s);
    }
  } else {
    g_last_error = "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Initialize the interpreter once; PYTHONPATH (set by the client env)
// must include the paddle_tpu checkout / site-packages.
bool ensure_python() {
  if (Py_IsInitialized() != 0) return true;
  Py_InitializeEx(0);
  if (Py_IsInitialized() == 0) return false;
  // park the GIL: Py_InitializeEx leaves THIS thread holding it, and a
  // second thread's PyGILState_Ensure would otherwise block forever —
  // defeating the per-thread-clone contract in the header
  PyEval_SaveThread();
  return true;
}

struct Gil {
  PyGILState_STATE state;
  Gil() : state(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state); }
};

}  // namespace

extern "C" {

struct PD_Config {
  std::string prefix;
};

struct PD_Tensor {
  PyObject* handle;  // borrowed semantics: predictor owns lifetimes via
                     // its handle dicts; we hold our own reference too
};

struct PD_Predictor {
  PyObject* obj = nullptr;
  std::vector<std::string> input_names;
  std::vector<std::string> output_names;
  std::vector<PD_Tensor*> tensors;  // handed-out handles, freed on destroy

  ~PD_Predictor() {
    for (PD_Tensor* t : tensors) {
      Py_XDECREF(t->handle);
      delete t;
    }
    Py_XDECREF(obj);
  }
};

const char* PD_GetLastError(void) { return g_last_error.c_str(); }

PD_Config* PD_ConfigCreate(void) { return new PD_Config(); }

void PD_ConfigSetModel(PD_Config* c, const char* prog_prefix,
                       const char* /*params_file*/) {
  if (c != nullptr && prog_prefix != nullptr) {
    std::string p(prog_prefix);
    const std::string suffix = ".pdmodel";
    if (p.size() > suffix.size() &&
        p.compare(p.size() - suffix.size(), suffix.size(), suffix) == 0) {
      p.resize(p.size() - suffix.size());
    }
    c->prefix = p;
  }
}

void PD_ConfigDestroy(PD_Config* c) { delete c; }

static bool refresh_names(PD_Predictor* p, const char* getter,
                          std::vector<std::string>* out) {
  PyObject* names = PyObject_CallMethod(p->obj, getter, nullptr);
  if (names == nullptr) {
    set_error_from_python();
    return false;
  }
  out->clear();
  PyObject* it = PyObject_GetIter(names);
  PyObject* item = nullptr;
  while (it != nullptr && (item = PyIter_Next(it)) != nullptr) {
    const char* s = PyUnicode_AsUTF8(item);
    if (s != nullptr) out->emplace_back(s);
    Py_DECREF(item);
  }
  Py_XDECREF(it);
  Py_DECREF(names);
  return true;
}

PD_Predictor* PD_PredictorCreate(PD_Config* c) {
  if (c == nullptr || !ensure_python()) {
    g_last_error = "python runtime unavailable";
    return nullptr;
  }
  Gil gil;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (mod == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PyObject* cfg =
      PyObject_CallMethod(mod, "Config", "s", c->prefix.c_str());
  PyObject* pred =
      cfg != nullptr
          ? PyObject_CallMethod(mod, "create_predictor", "O", cfg)
          : nullptr;
  Py_XDECREF(cfg);
  Py_DECREF(mod);
  if (pred == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PD_Predictor* p = new PD_Predictor();
  p->obj = pred;
  if (!refresh_names(p, "get_input_names", &p->input_names)) {
    delete p;
    return nullptr;
  }
  return p;
}

PD_Predictor* PD_PredictorClone(PD_Predictor* p) {
  if (p == nullptr) return nullptr;
  Gil gil;
  PyObject* cl = PyObject_CallMethod(p->obj, "clone", nullptr);
  if (cl == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PD_Predictor* q = new PD_Predictor();
  q->obj = cl;
  q->input_names = p->input_names;
  return q;
}

void PD_PredictorDestroy(PD_Predictor* p) {
  if (p == nullptr) return;
  Gil gil;
  delete p;
}

int PD_PredictorGetInputNum(PD_Predictor* p) {
  return p != nullptr ? static_cast<int>(p->input_names.size()) : 0;
}

int PD_PredictorGetOutputNum(PD_Predictor* p) {
  return p != nullptr ? static_cast<int>(p->output_names.size()) : 0;
}

const char* PD_PredictorGetInputName(PD_Predictor* p, int i) {
  if (p == nullptr || i < 0 ||
      i >= static_cast<int>(p->input_names.size()))
    return nullptr;
  return p->input_names[static_cast<size_t>(i)].c_str();
}

const char* PD_PredictorGetOutputName(PD_Predictor* p, int i) {
  if (p == nullptr || i < 0 ||
      i >= static_cast<int>(p->output_names.size()))
    return nullptr;
  return p->output_names[static_cast<size_t>(i)].c_str();
}

static PD_Tensor* get_handle(PD_Predictor* p, const char* getter,
                             const char* name) {
  if (p == nullptr || name == nullptr) return nullptr;
  Gil gil;
  PyObject* h = PyObject_CallMethod(p->obj, getter, "s", name);
  if (h == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PD_Tensor* t = new PD_Tensor{h};
  p->tensors.push_back(t);
  return t;
}

PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor* p, const char* name) {
  return get_handle(p, "get_input_handle", name);
}

PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor* p, const char* name) {
  return get_handle(p, "get_output_handle", name);
}

// per-handle staged shape: reference clients call Reshape then CopyFromCpu
void PD_TensorReshape(PD_Tensor* t, int ndim, const int64_t* shape) {
  if (t == nullptr) return;
  Gil gil;
  PyObject* tup = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(tup, i, PyLong_FromLongLong(shape[i]));
  }
  // stage on the python handle; consumed by the next CopyFromCpu
  if (PyObject_SetAttrString(t->handle, "_capi_shape", tup) != 0) {
    set_error_from_python();
  }
  Py_DECREF(tup);
}

void PD_TensorCopyFromCpuFloat(PD_Tensor* t, const float* data) {
  if (t == nullptr || data == nullptr) return;
  Gil gil;
  PyObject* shape = PyObject_GetAttrString(t->handle, "_capi_shape");
  if (shape == nullptr) {
    PyErr_Clear();
    g_last_error = "PD_TensorReshape must precede CopyFromCpu";
    return;
  }
  Py_ssize_t nd = PyTuple_Size(shape);
  long long total = 1;
  for (Py_ssize_t i = 0; i < nd; ++i) {
    total *= PyLong_AsLongLong(PyTuple_GET_ITEM(shape, i));
  }
  // bytes -> numpy.frombuffer -> reshape, then handle.copy_from_cpu
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(total * sizeof(float)));
  PyObject* flat =
      np != nullptr && bytes != nullptr
          ? PyObject_CallMethod(np, "frombuffer", "Os", bytes, "float32")
          : nullptr;
  PyObject* arr =
      flat != nullptr
          ? PyObject_CallMethod(flat, "reshape", "O", shape)
          : nullptr;
  PyObject* r =
      arr != nullptr
          ? PyObject_CallMethod(t->handle, "copy_from_cpu", "O", arr)
          : nullptr;
  if (r == nullptr) set_error_from_python();
  Py_XDECREF(r);
  Py_XDECREF(arr);
  Py_XDECREF(flat);
  Py_XDECREF(bytes);
  Py_XDECREF(np);
  Py_DECREF(shape);
}

int PD_PredictorRun(PD_Predictor* p) {
  if (p == nullptr) return -1;
  Gil gil;
  PyObject* r = PyObject_CallMethod(p->obj, "run", nullptr);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  if (!refresh_names(p, "get_output_names", &p->output_names)) return -1;
  return 0;
}

static PyObject* tensor_numpy(PD_Tensor* t) {
  // handle.copy_to_cpu() -> np.ascontiguousarray(float32)
  PyObject* arr = PyObject_CallMethod(t->handle, "copy_to_cpu", nullptr);
  if (arr == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* c =
      np != nullptr
          ? PyObject_CallMethod(np, "ascontiguousarray", "Os", arr,
                                "float32")
          : nullptr;
  if (c == nullptr) set_error_from_python();
  Py_XDECREF(np);
  Py_DECREF(arr);
  return c;
}

int PD_TensorGetNumDims(PD_Tensor* t) {
  if (t == nullptr) return 0;
  Gil gil;
  PyObject* shape = PyObject_CallMethod(t->handle, "shape", nullptr);
  if (shape == nullptr) {
    set_error_from_python();
    return 0;
  }
  int n = static_cast<int>(PyObject_Length(shape));
  Py_DECREF(shape);
  return n;
}

void PD_TensorGetShape(PD_Tensor* t, int64_t* shape_out) {
  if (t == nullptr || shape_out == nullptr) return;
  Gil gil;
  PyObject* shape = PyObject_CallMethod(t->handle, "shape", nullptr);
  if (shape == nullptr) {
    set_error_from_python();
    return;
  }
  Py_ssize_t n = PyObject_Length(shape);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PySequence_GetItem(shape, i);
    shape_out[i] = item != nullptr ? PyLong_AsLongLong(item) : 0;
    Py_XDECREF(item);
  }
  Py_DECREF(shape);
}

void PD_TensorCopyToCpuFloat(PD_Tensor* t, float* data) {
  if (t == nullptr || data == nullptr) return;
  Gil gil;
  PyObject* c = tensor_numpy(t);
  if (c == nullptr) return;
  PyObject* bytes = PyObject_CallMethod(c, "tobytes", nullptr);
  if (bytes != nullptr) {
    char* buf = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(bytes, &buf, &len) == 0) {
      std::memcpy(data, buf, static_cast<size_t>(len));
    }
    Py_DECREF(bytes);
  } else {
    set_error_from_python();
  }
  Py_DECREF(c);
}

void PD_TensorDestroy(PD_Tensor* t) {
  // handle refs are released by PD_PredictorDestroy; nothing to do for
  // the opaque pointer itself (it stays in the predictor's list)
  (void)t;
}

}  // extern "C"
