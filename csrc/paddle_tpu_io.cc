// paddle_tpu native io core (reference analog: the C++ DataLoader
// workers + LoDTensorBlockingQueue machinery under paddle/fluid/operators/
// reader/ — unverified, SURVEY.md §0).
//
// TPU-first division of labor: XLA owns device compute; the host-side
// hot loops the GIL would serialize live here —
//   * gather_rows: multithreaded batch assembly (row gather → one
//     contiguous buffer ready for jax.device_put; H2D wants contiguity)
//   * shuffle_indices: Fisher–Yates over an int64 index buffer with a
//     splitmix64 stream (epoch shuffles of 100M-sample datasets)
//   * pack_varlen: pad/pack variable-length token id rows into a dense
//     int32 batch + lengths (NLP loader hot path)
//
// Plain C ABI (ctypes-loadable), C++17, no deps beyond pthread.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Copy rows `indices[0..n_idx)` of `src` (row_bytes each) into `dst`
// contiguously, splitting the index range over `n_threads` workers.
// Returns 0 on success, -1 on bad args.
int ptpu_gather_rows(const uint8_t* src, int64_t n_rows, int64_t row_bytes,
                     const int64_t* indices, int64_t n_idx, uint8_t* dst,
                     int n_threads) {
  if (!src || !dst || !indices || row_bytes <= 0 || n_idx < 0) return -1;
  if (n_threads < 1) n_threads = 1;
  std::atomic<int> bad{0};
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t r = indices[i];
      if (r < 0 || r >= n_rows) {
        bad.store(1, std::memory_order_relaxed);
        return;
      }
      std::memcpy(dst + i * row_bytes, src + r * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
  };
  if (n_threads == 1 || n_idx < 4 * n_threads) {
    worker(0, n_idx);
  } else {
    std::vector<std::thread> ts;
    int64_t chunk = (n_idx + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk > n_idx ? n_idx : lo + chunk;
      if (lo >= hi) break;
      ts.emplace_back(worker, lo, hi);
    }
    for (auto& t : ts) t.join();
  }
  return bad.load() ? -1 : 0;
}

static inline uint64_t splitmix64(uint64_t* s) {
  uint64_t z = (*s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

// In-place Fisher–Yates over buf[0..n). Deterministic in `seed`.
void ptpu_shuffle_indices(int64_t* buf, int64_t n, uint64_t seed) {
  uint64_t s = seed ? seed : 0x853c49e6748fea9bull;
  for (int64_t i = n - 1; i > 0; --i) {
    uint64_t j = splitmix64(&s) % static_cast<uint64_t>(i + 1);
    int64_t tmp = buf[i];
    buf[i] = buf[static_cast<int64_t>(j)];
    buf[static_cast<int64_t>(j)] = tmp;
  }
}

// Pack `n_rows` variable-length int32 rows (concatenated in `flat`,
// row i spanning offsets[i]..offsets[i+1]) into dst[n_rows, max_len]
// padded with pad_id; writes each row's length into lengths. Rows longer
// than max_len are truncated. Returns 0, or -1 on bad args.
int ptpu_pack_varlen(const int32_t* flat, const int64_t* offsets,
                     int64_t n_rows, int64_t max_len, int32_t pad_id,
                     int32_t* dst, int32_t* lengths, int n_threads) {
  if (!flat || !offsets || !dst || !lengths || max_len <= 0) return -1;
  if (n_threads < 1) n_threads = 1;
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t start = offsets[i], stop = offsets[i + 1];
      int64_t len = stop - start;
      if (len > max_len) len = max_len;
      lengths[i] = static_cast<int32_t>(len);
      int32_t* row = dst + i * max_len;
      std::memcpy(row, flat + start, static_cast<size_t>(len) * 4);
      for (int64_t j = len; j < max_len; ++j) row[j] = pad_id;
    }
  };
  if (n_threads == 1 || n_rows < 4 * n_threads) {
    worker(0, n_rows);
  } else {
    std::vector<std::thread> ts;
    int64_t chunk = (n_rows + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = lo + chunk > n_rows ? n_rows : lo + chunk;
      if (lo >= hi) break;
      ts.emplace_back(worker, lo, hi);
    }
    for (auto& t : ts) t.join();
  }
  return 0;
}

int ptpu_version() { return 1; }

}  // extern "C"
