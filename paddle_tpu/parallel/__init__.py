"""Mesh/sharding substrate shared by fleet, auto_parallel and the models."""
from .mesh import (  # noqa: F401
    set_mesh, get_mesh, has_mesh, mesh_axis_size, shard_value,
    constraint, replicate_value, MeshScope,
)
