"""Global device-mesh state — the TPU-native replacement for the
reference's communication-group machinery (SURVEY.md §2.3 TPU mapping).

Where the reference builds ProcessGroupNCCL rings per topology axis, here
``fleet.init`` (or auto-parallel) installs ONE ``jax.sharding.Mesh`` with
named axes (``dp``, ``sharding``, ``sep``, ``mp`` — pipeline stages get
per-stage sub-meshes) and layers place/constrain arrays with
``PartitionSpec``s; XLA GSPMD inserts the ICI collectives.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_GLOBAL_MESH: Mesh | None = None


def set_mesh(mesh: Mesh | None):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh() -> Mesh | None:
    return _GLOBAL_MESH


def has_mesh() -> bool:
    return _GLOBAL_MESH is not None


def mesh_axis_size(axis: str) -> int:
    if _GLOBAL_MESH is None or axis not in _GLOBAL_MESH.shape:
        return 1
    return int(_GLOBAL_MESH.shape[axis])


class MeshScope:
    """Temporarily install a mesh (used by per-stage pipeline execution)."""

    def __init__(self, mesh):
        self._mesh = mesh

    def __enter__(self):
        global _GLOBAL_MESH
        self._saved = _GLOBAL_MESH
        _GLOBAL_MESH = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        global _GLOBAL_MESH
        _GLOBAL_MESH = self._saved
        return False


# pass-through marker for constraint(): "leave this dim's sharding to the
# propagation pass" (valid only under a trace; eager constraint is identity)
UNCONSTRAINED = PartitionSpec.UNCONSTRAINED


def _named_sharding(spec):
    if _GLOBAL_MESH is None:
        return None
    if not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec)
    # drop axis names the mesh doesn't have (e.g. sep unused)
    cleaned = []
    for entry in spec:
        if entry is None:
            cleaned.append(None)
        elif entry is PartitionSpec.UNCONSTRAINED:
            cleaned.append(entry)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in _GLOBAL_MESH.shape)
            cleaned.append(kept if kept else None)
        else:
            cleaned.append(entry if entry in _GLOBAL_MESH.shape else None)
    return NamedSharding(_GLOBAL_MESH, PartitionSpec(*cleaned))


def _divisible(value, spec):
    """Check every sharded dim divides by the axis size product."""
    if _GLOBAL_MESH is None:
        return False
    shape = np.shape(value)
    for dim, entry in enumerate(spec):
        if entry is None or entry is PartitionSpec.UNCONSTRAINED:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        size = 1
        for a in axes:
            size *= int(_GLOBAL_MESH.shape.get(a, 1))
        if size > 1 and (dim >= len(shape) or shape[dim] % size != 0):
            return False
    return True


def spec_axes(spec):
    """Flatten a PartitionSpec (or spec tuple) into the mesh-axis names
    it uses, in order; UNCONSTRAINED and None entries contribute none."""
    out = []
    for entry in spec:
        if entry is None or entry is PartitionSpec.UNCONSTRAINED:
            continue
        out.extend((entry,) if isinstance(entry, str) else entry)
    return out


def merged_dim0_spec(shape, base_spec, mesh, axis):
    """Merge ``axis`` into dim 0 of ``base_spec``, MINOR (last in the
    dim-entry tuple): for a TP-sharded tensor this subdivides each ``mp``
    chunk so every device's ZeRO shard is a sub-slice of its own TP
    shard — ``(axis, 'mp')`` would interleave across mp chunks and force
    a cross-device reshard every step. Returns the base spec unchanged
    when dim 0 doesn't divide by the combined axis sizes or ``axis`` is
    already present. Shared by the ZeRO-1/2 optimizer-state placement
    (jit/train.py) and the stage-3 param placement (group_sharded.py)."""
    size = int(mesh.shape.get(axis, 1))
    ndim = len(shape)
    if size <= 1 or ndim == 0:
        return PartitionSpec(*base_spec)
    parts = list(base_spec) + [None] * (ndim - len(base_spec))
    d0 = parts[0]
    existing = () if d0 is None else (
        (d0,) if isinstance(d0, str) else tuple(d0))
    existing_size = 1
    for a in existing:
        existing_size *= int(mesh.shape.get(a, 1))
    if axis not in existing and shape[0] % (size * existing_size) == 0:
        parts[0] = (*existing, axis) if existing else axis
    return PartitionSpec(*parts)


def shard_value(value, *spec):
    """device_put a concrete array with the given PartitionSpec entries
    (falls back to replication for non-divisible dims)."""
    sharding = _named_sharding(spec)
    if sharding is None:
        return value
    if not _divisible(value, tuple(spec)):
        sharding = _named_sharding(())
    return jax.device_put(value, sharding)


def replicate_value(value):
    sharding = _named_sharding(())
    if sharding is None:
        return value
    return jax.device_put(value, sharding)


def constraint(value, *spec):
    """Sharding constraint usable both eagerly and inside traces; identity
    when no mesh is installed (single-device runs stay zero-cost)."""
    sharding = _named_sharding(spec)
    if sharding is None:
        return value
    if isinstance(value, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(value, sharding)
    if any(e is PartitionSpec.UNCONSTRAINED for e in spec):
        # UNCONSTRAINED is a propagation-pass concept; a concrete array
        # already carries its sharding — nothing to do eagerly
        return value
    if not _divisible(value, tuple(spec)):
        return value
    return jax.device_put(value, sharding)
