"""paddle.device — device introspection + memory stats (reference:
python/paddle/device/__init__.py and device/cuda/ — unverified,
SURVEY.md §0; round-1 verdict L2 row: "no device-introspection/
memory-stats surface").

TPU mapping: the reference's per-allocator CUDA counters map to PJRT's
``device.memory_stats()`` (bytes_in_use / peak_bytes_in_use /
bytes_limit). The ``cuda`` submodule alias keeps reference call sites
(``paddle.device.cuda.max_memory_allocated()``) working against the
accelerator actually present. Streams are XLA's concern: ``synchronize``
is a barrier on all in-flight computations, and Stream/Event are no-op
ordering facades (everything on one device is already ordered)."""
from __future__ import annotations

import types

import jax

from ..core.place import (  # noqa: F401
    set_device, get_device, current_place, CPUPlace, CUDAPlace, TPUPlace,
)

__all__ = [
    "set_device", "get_device", "get_all_device_type",
    "get_available_device", "device_count", "synchronize",
    "memory_allocated", "max_memory_allocated", "memory_reserved",
    "max_memory_reserved", "empty_cache", "get_device_properties",
    "cuda", "Stream", "Event",
]


def _devices():
    return jax.devices()


def _resolve_id(device):
    """paddle device arg → device index: int | 'tpu:1' | 'gpu:0' | Place
    | None (current)."""
    if device is None:
        return 0
    if isinstance(device, int):
        return device
    did = getattr(device, "device_id", None)  # Place
    if did is not None:
        return int(did)
    name = str(device)
    if ":" in name:
        return int(name.rsplit(":", 1)[1])
    return 0


def _device(device=None):
    devs = _devices()
    return devs[_resolve_id(device)]


def get_all_device_type():
    return sorted({d.platform for d in _devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in _devices()]


def device_count(device_type=None):
    if device_type is None:
        return len(_devices())
    return sum(1 for d in _devices() if d.platform == str(device_type))


def synchronize(device=None):
    """Block until all in-flight computations on ``device`` finish."""
    (jax.device_put(0.0, _device(device)) + 0).block_until_ready()


def _stats(device=None):
    d = _device(device)
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None):
    """Live bytes on the device (PJRT bytes_in_use); 0 when the backend
    doesn't report (CPU, tunneled TPU)."""
    return int(_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None):
    return int(_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None):
    s = _stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None):
    s = _stats(device)
    return int(s.get("peak_bytes_reserved", s.get("peak_bytes_in_use", 0)))


def empty_cache():
    """XLA owns the allocator; nothing to flush (API-parity no-op)."""
    return None


def get_device_properties(device=None):
    d = _device(device)
    s = _stats(device)
    return types.SimpleNamespace(
        name=d.device_kind,
        total_memory=int(s.get("bytes_limit", 0)),
        major=0, minor=0,
        multi_processor_count=len(_devices()),
    )


class Stream:
    """Ordering facade: XLA serializes per-device execution, so a stream
    is just a handle (reference paddle.device.Stream parity)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize(self.device)

    def wait_event(self, event):
        return None

    def wait_stream(self, stream):
        return None

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._stream = None

    def record(self, stream=None):
        self._stream = stream
        return None

    def query(self):
        return True

    def synchronize(self):
        synchronize(self._stream.device if self._stream else None)


# reference spelling: paddle.device.cuda.* — same accelerator underneath
cuda = types.SimpleNamespace(
    device_count=device_count,
    memory_allocated=memory_allocated,
    max_memory_allocated=max_memory_allocated,
    memory_reserved=memory_reserved,
    max_memory_reserved=max_memory_reserved,
    empty_cache=empty_cache,
    synchronize=synchronize,
    get_device_properties=get_device_properties,
    Stream=Stream,
    Event=Event,
)
