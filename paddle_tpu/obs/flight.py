"""Per-request flight recorder: a bounded journal of every lifecycle
event a request passes through on the host scheduler — submit, admit
(with pool/block context), prefill chunks, first token, decode-quantum
yields, speculative rounds with acceptance, preempt/resume (the front
door's eviction pair, with the recompute debt), the resilience tier's
fault/retry/degrade/restore events (serving/faults.py), the cluster
tier's route/handoff placements (serving/cluster.py), retire — with
DUMP-ON-ANOMALY: when a retiring request's TTFT or e2e latency crosses
its SLO threshold (obs/slo.py), or its preemptions re-computed more
cached tokens than ``recompute_threshold`` allows (the cost ledger's
waste signal, obs/attribution.py), the full journal is captured into a
bounded anomaly buffer and exportable as schema-validated JSON-lines,
so a slow tail request is *explainable* after the fact, not just a
histogram bucket (reference: the request-level profile the reference's
serving stack can dump per query — unverified, SURVEY.md §0).

Hot-path-safe by the same construction as :mod:`.trace`: one event is
a dict append into a bounded per-request list (``max_events`` each,
drops counted), the live-journal table is bounded (``max_live``,
overflow requests ride unjournaled and are counted), and the anomaly
buffer is bounded (``max_anomalies``, drops counted). Nothing here
imports jax; every hook runs at the host scheduler boundaries PR 5
established, so the compiled quantum's ``max_host_callbacks=0`` budget
and golden fingerprint are unchanged with the recorder on.

``validate_flight_records`` / ``load_flight_records`` round-trip the
anomaly-record schema exactly like ``validate_chrome_trace`` does for
traces; records are one JSON object per line (JSONL) so dumps stream
and concatenate.
"""
from __future__ import annotations

import json

__all__ = ["FlightRecorder", "validate_flight_records",
           "load_flight_records", "EVENT_KINDS"]

EVENT_KINDS = ("submit", "admit", "prefill_chunk", "first_token",
               "decode_quantum", "spec_round", "preempt", "resume",
               "shed", "retire", "fault", "retry", "degrade", "restore",
               "route", "handoff")

_ANOMALY_SIGNALS = ("ttft_seconds", "e2e_latency_seconds")


class FlightRecorder:
    """Bounded per-request journals + the anomaly dump buffer.

    Args:
        slo: an :class:`~paddle_tpu.obs.slo.SLOSet` (or anything with
            ``threshold(signal)``) the dump triggers are read from —
            the tightest declared ``ttft_seconds`` /
            ``e2e_latency_seconds`` thresholds.
        ttft_threshold / e2e_threshold: explicit trigger overrides in
            seconds (win over ``slo``); with neither an SLO nor an
            override for a signal, that signal never triggers a dump.
        recompute_threshold: dump when a retiring request's journaled
            preemptions re-computed MORE than this many cached tokens
            (the recompute-waste spike the cost ledger's
            useful-token-fraction gauge prices; obs/attribution.py).
            ``None`` (default) never triggers; the count is summed
            from the journal's own ``preempt`` events, so no new
            engine plumbing is involved.
        max_live: journal table capacity — requests submitted past it
            ride unjournaled (``dropped_requests`` counts them).
        max_events: per-request journal bound (overflow counted in the
            journal's ``dropped_events``).
        max_anomalies: anomaly buffer bound (``dropped_anomalies``
            counts captures that found it full).
    """

    def __init__(self, slo=None, ttft_threshold=None, e2e_threshold=None,
                 recompute_threshold=None, max_live=1024,
                 max_events=256, max_anomalies=64):
        def _trigger(explicit, signal):
            if explicit is not None:
                return float(explicit)
            if slo is not None and hasattr(slo, "threshold"):
                return slo.threshold(signal)
            return None

        self.ttft_threshold = _trigger(ttft_threshold, "ttft_seconds")
        self.e2e_threshold = _trigger(e2e_threshold,
                                      "e2e_latency_seconds")
        self.recompute_threshold = (None if recompute_threshold is None
                                    else float(recompute_threshold))
        self.max_live = int(max_live)
        self.max_events = int(max_events)
        self.max_anomalies = int(max_anomalies)
        self._live = {}          # req_id -> journal dict
        self.anomalies = []      # captured journals, bounded
        self.dropped_requests = 0
        self.dropped_anomalies = 0
        self.retired_total = 0
        self.captured_total = 0

    def __len__(self):
        return len(self._live)

    @property
    def live_count(self):
        return len(self._live)

    # -- journaling --------------------------------------------------------
    def _event(self, req, kind, t, _force=False, **fields):
        j = self._live.get(str(req.req_id))
        if j is None:
            return  # unjournaled (table overflow) or unknown request
        if not _force and len(j["events"]) >= self.max_events:
            j["dropped_events"] += 1
            return  # terminal events (_force) always land, so a
        ev = {"t": float(t), "kind": kind}  # captured journal stays
        ev.update(fields)                   # schema-valid (ends at
        j["events"].append(ev)              # retire/shed)

    def on_submit(self, req, t):
        rid = str(req.req_id)
        if rid not in self._live and len(self._live) >= self.max_live:
            self.dropped_requests += 1
            return
        self._live[rid] = {
            "req_id": rid,
            "prompt_len": int(req.prompt_len),
            "max_new_tokens": int(req.max_new_tokens),
            "events": [],
            "dropped_events": 0,
        }
        self._event(req, "submit", t)

    def on_admit(self, req, t, queue_wait=None, blocks_reserved=None,
                 pool_free_blocks=None, pool_blocks_in_use=None,
                 cached_blocks=None, novel_blocks=None):
        """``cached_blocks`` / ``novel_blocks`` split the admission's
        block demand between prefix-cache aliases (no prefill compute,
        no fresh residency) and blocks it must still populate."""
        self._event(req, "admit", t, slot=int(req.slot),
                    queue_wait_s=queue_wait,
                    blocks_reserved=blocks_reserved,
                    pool_free_blocks=pool_free_blocks,
                    pool_blocks_in_use=pool_blocks_in_use,
                    cached_blocks=cached_blocks,
                    novel_blocks=novel_blocks)

    def on_prefill_chunk(self, req, t, tokens, pos):
        """``tokens`` prompt tokens entered the pool this mixed step;
        ``pos`` is the prefill cursor AFTER the chunk."""
        self._event(req, "prefill_chunk", t, tokens=int(tokens),
                    pos=int(pos))

    def on_first_token(self, req, t, ttft):
        self._event(req, "first_token", t, ttft_s=float(ttft))

    def on_quantum_tokens(self, req, t, tokens):
        """Tokens this request gained from one jitted decode quantum."""
        self._event(req, "decode_quantum", t, tokens=int(tokens))

    def on_spec_round(self, req, t, proposed, accepted, emitted):
        """One speculative round's per-request outcome: ``proposed``
        draft tokens, ``accepted`` of them, ``emitted`` appended to the
        stream (acceptance prefix + bonus, capped by eos/max-new)."""
        self._event(req, "spec_round", t, proposed=int(proposed),
                    accepted=int(accepted), emitted=int(emitted))

    def on_preempt(self, req, t, cached_tokens=0, tokens_emitted=0):
        """The request lost its slot under pool pressure: its cached KV
        (``cached_tokens``) went back to the pool and will be
        re-prefilled on resume; the emitted stream is untouched."""
        self._event(req, "preempt", t, cached_tokens=int(cached_tokens),
                    tokens_emitted=int(tokens_emitted))

    def on_resume(self, req, t, slot=None, prefill_tokens=0):
        """The preempted request re-admitted: ``prefill_tokens`` =
        prompt + emitted tokens to re-prefill before the stream
        continues."""
        self._event(req, "resume", t,
                    slot=(None if slot is None else int(slot)),
                    prefill_tokens=int(prefill_tokens),
                    preemptions=int(req.preemptions))

    def on_fault(self, req, t, site=None, kind=None):
        """An injected (or contained) fault touched this request —
        either a fault fired while the request was an active dispatch
        row, or the bisect quarantine error-finished it
        (``site="quarantine"``). The fault's own kind rides in the
        ``fault`` field (``kind`` is the event kind)."""
        self._event(req, "fault", t, site=site, fault=kind)

    def on_retry(self, req, t, kind=None, attempt=None, backoff_s=None):
        """The dispatch this request rode in was retried after an
        injected fault (``attempt`` is 1-based; the quantum kind rides
        in ``quantum``)."""
        self._event(req, "retry", t, quantum=kind,
                    attempt=(None if attempt is None else int(attempt)),
                    backoff_s=backoff_s)

    def on_degrade(self, req, t, mode=None):
        """A degradation-ladder rung activated while this request was
        live (``spec_disabled`` | ``pool_rebuild``) — journaled per
        live request so an anomaly dump shows the mode switch inline
        with the request's own timeline."""
        self._event(req, "degrade", t, mode=mode)

    def on_restore(self, req, t, tokens_resumed=0):
        """The request was re-admitted into a restored engine
        (snapshot -> restore crash recovery): ``tokens_resumed`` tokens
        were already emitted pre-crash and will be re-prefilled, not
        re-emitted."""
        self._event(req, "restore", t,
                    tokens_resumed=int(tokens_resumed))

    def on_route(self, req, t, replica=None, reason=None):
        """A cluster router placed this request on a replica
        (``reason`` = ``affinity`` | ``balance`` | ``failover``).
        Journaled on the CHOSEN replica's recorder, after the engine's
        own ``submit`` event, so the journal still opens at submit."""
        self._event(req, "route", t, replica=replica, reason=reason)

    def on_handoff(self, req, t, src=None, dst=None, tokens_prefilled=0):
        """Disaggregated prefill->decode hand-off: the prefill replica
        ``src`` published the prompt's blocks and the decode replica
        ``dst`` re-admitted the request via recompute-on-resume."""
        self._event(req, "handoff", t, src=src, dst=dst,
                    tokens_prefilled=int(tokens_prefilled))

    def on_shed(self, req, t, reason="shed"):
        """A request refused admission by a load-shedding policy: its
        (short) journal is always worth keeping — shedding IS an
        anomaly — so it captures unconditionally."""
        self._event(req, "shed", t, _force=True, reason=str(reason))
        self._finish(req, {"shed": {"value": 1.0, "threshold": 0.0}},
                     reason=str(reason), t=t, tokens=0)

    # -- retirement + anomaly capture --------------------------------------
    def on_retire(self, req, t, ttft=None, e2e=None, reason=None):
        """Journal the retirement, then apply the dump rule: if the
        request's TTFT or e2e crossed its threshold, capture the full
        journal into the anomaly buffer; either way the live entry is
        released."""
        self.retired_total += 1
        self._event(req, "retire", t, _force=True, ttft_s=ttft,
                    e2e_s=e2e, reason=reason, tokens=len(req.tokens))
        signals = {}
        if (self.ttft_threshold is not None and ttft is not None
                and ttft > self.ttft_threshold):
            signals["ttft_seconds"] = {
                "value": float(ttft), "threshold": self.ttft_threshold}
        if (self.e2e_threshold is not None and e2e is not None
                and e2e > self.e2e_threshold):
            signals["e2e_latency_seconds"] = {
                "value": float(e2e), "threshold": self.e2e_threshold}
        if self.recompute_threshold is not None:
            j = self._live.get(str(req.req_id))
            recomputed = sum(
                ev.get("cached_tokens", 0) for ev in j["events"]
                if ev["kind"] == "preempt") if j else 0
            if recomputed > self.recompute_threshold:
                signals["recomputed_tokens"] = {
                    "value": float(recomputed),
                    "threshold": self.recompute_threshold}
        if signals:
            self._finish(req, signals, reason=reason, t=t,
                         tokens=len(req.tokens))
        else:
            self._live.pop(str(req.req_id), None)

    def _finish(self, req, signals, reason, t, tokens):
        j = self._live.pop(str(req.req_id), None)
        if j is None:
            return  # was unjournaled; nothing to capture
        j["anomaly"] = {"t": float(t), "signals": signals,
                        "reason": reason, "tokens": int(tokens)}
        self.captured_total += 1
        if len(self.anomalies) >= self.max_anomalies:
            self.dropped_anomalies += 1
            return
        self.anomalies.append(j)

    # -- export ------------------------------------------------------------
    def stats(self):
        return {
            "live": len(self._live),
            "anomalies": len(self.anomalies),
            "captured_total": self.captured_total,
            "retired_total": self.retired_total,
            "dropped_requests": self.dropped_requests,
            "dropped_anomalies": self.dropped_anomalies,
            "ttft_threshold": self.ttft_threshold,
            "e2e_threshold": self.e2e_threshold,
            "recompute_threshold": self.recompute_threshold,
        }

    def records(self):
        """The captured anomaly records (schema-validated copies)."""
        return validate_flight_records(
            [json.loads(json.dumps(j)) for j in self.anomalies])

    def jsonl(self):
        """One JSON object per line — streams and concatenates."""
        return "".join(json.dumps(j, sort_keys=True) + "\n"
                       for j in self.records())

    def save(self, path):
        with open(path, "w") as f:
            f.write(self.jsonl())
        return path


def _expect(cond, ctx, msg):
    if not cond:
        raise ValueError(f"{ctx}: {msg}")


def validate_flight_records(records):
    """Schema check for anomaly dumps — the JSONL counterpart of
    ``validate_chrome_trace``; raises ValueError naming the first
    offending record/field. Returns ``records``."""
    _expect(isinstance(records, list), "flight records",
            f"expected a list of records, got {type(records).__name__}")
    for i, rec in enumerate(records):
        ctx = f"records[{i}]"
        _expect(isinstance(rec, dict), ctx, "record must be a dict")
        for k in ("req_id", "prompt_len", "max_new_tokens", "events",
                  "dropped_events", "anomaly"):
            _expect(k in rec, ctx, f"missing {k!r}")
        _expect(isinstance(rec["req_id"], str), ctx,
                "req_id must be a string")
        _expect(isinstance(rec["dropped_events"], int)
                and rec["dropped_events"] >= 0, ctx,
                "dropped_events must be a non-negative int")
        an = rec["anomaly"]
        _expect(isinstance(an, dict) and an.get("signals"), ctx,
                "anomaly.signals must be a non-empty dict")
        for sig, d in an["signals"].items():
            sctx = f"{ctx}.anomaly.signals[{sig!r}]"
            _expect(isinstance(d, dict), sctx, "must be a dict")
            for k in ("value", "threshold"):
                _expect(isinstance(d.get(k), (int, float)), sctx,
                        f"{k} must be a number")
        evs = rec["events"]
        _expect(isinstance(evs, list) and evs, ctx,
                "events must be a non-empty list")
        last_t = None
        for jn, ev in enumerate(evs):
            ectx = f"{ctx}.events[{jn}]"
            _expect(isinstance(ev, dict), ectx, "event must be a dict")
            _expect(ev.get("kind") in EVENT_KINDS, ectx,
                    f"kind must be one of {EVENT_KINDS}, got "
                    f"{ev.get('kind')!r}")
            _expect(isinstance(ev.get("t"), (int, float)), ectx,
                    "t must be a number")
            _expect(last_t is None or ev["t"] >= last_t, ectx,
                    "events must be time-ordered")
            last_t = ev["t"]
        _expect(evs[0]["kind"] == "submit", ctx,
                "journal must start at submit")
        _expect(evs[-1]["kind"] in ("retire", "shed"), ctx,
                "journal must end at retire/shed")
    return records


def load_flight_records(path):
    """Load + validate a saved JSONL dump; returns the record list."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return validate_flight_records(records)
