"""Serving-engine instrumentation: every hook here runs ON THE HOST at
a scheduler boundary (submit/admit, mixed prefill step, decode-quantum
or spec-round dispatch, retire) — never inside the jitted quantum, so
the compiled program the ``serving_decode_step`` /
``speculative_verify_step`` budgets pin is byte-identical with
observability enabled (the golden-fingerprint gate proves it).

:class:`ServingObs` owns a :class:`~paddle_tpu.obs.registry.
MetricsRegistry` (always-on: counters/gauges/histograms are dict ops)
and an optional :class:`~paddle_tpu.obs.trace.TraceRecorder` (per-
request lifecycle spans on per-slot tracks, quantum spans + counter
tracks on the engine track — Perfetto-loadable). The engine's legacy
``stats`` dict survives as :class:`_LegacyStatsView`, a thin
MutableMapping over the same registry counters, so pre-observability
callers (benches, tests) read/reset the exact values the registry
exports.

Exported serving metrics (all host-boundary):

- counters: ``serving_requests_{submitted,admitted,finished}_total``,
  ``serving_tokens_emitted_total`` (one bump per token actually
  appended to a request — the stream-match invariant the obs tests
  assert), the front door's overload counters
  ``serving_requests_{shed,preempted,resumed}_total`` /
  ``serving_tokens_recomputed_total`` / ``serving_drains_total``
  (serving/frontend.py), the prefix-cache counters
  ``serving_prefix_cache_{hits,misses,cow_copies,shared_blocks}_total``
  ``{pool=target|draft}`` (synced from the pool's monotonic counters
  at step boundaries when the engine runs ``prefix_cache=True``), the
  resilience counters ``serving_faults_injected_total{site,kind}`` /
  ``serving_quantum_retries_total{kind}`` /
  ``serving_watchdog_trips_total{kind}`` /
  ``serving_degrades_total{mode}`` / ``serving_pool_rebuilds_total`` /
  ``serving_quarantines_total{kind=poison|prefix}`` /
  ``serving_restores_total`` (serving/faults.py +
  serving/resilience.py, all synced at step edges), plus
  the legacy ``serving_*_total`` counters behind ``engine.stats``.
- histograms: ``serving_queue_wait_seconds``, ``serving_ttft_seconds``
  (observed exactly once per request, at the prefill-completion step
  that emits its first token), ``serving_e2e_latency_seconds``,
  ``serving_inter_token_seconds`` (per-request mean at retirement),
  ``serving_quantum_seconds{kind=decode|spec_round|mixed}``.
- gauges: ``serving_tokens_per_second_window`` (trailing-window
  throughput), ``serving_spec_acceptance_rate`` (per-round),
  ``serving_slots_occupied``, ``serving_pool_{blocks_in_use,
  free_blocks,utilization}{pool=target|draft}``,
  ``serving_pool_{bytes,per_chip_bytes}{pool=...,kv_dtype=float|int8}``
  (dtype-aware residency: actual itemsize x elements + the int8
  pools' f32 scale rows — the gauge a quantized engine's ~2x
  capacity win shows up on),
  ``serving_prefix_cache_cached_block_fraction{pool=target|draft}``
  (index-held blocks over blocks in use), and the TP census pair
  ``serving_collective_{bytes,count}_total`` (unlabeled totals plus a
  ``{kind=all-reduce|...}`` split) — bytes/ops ONE compiled quantum
  dispatch moves over mesh collectives, read off the executable's HLO
  at engine build (:meth:`ServingObs.set_quantum_collectives`), never
  from runtime callbacks.
- cost ledger (obs/attribution.py, owned as ``obs.ledger``):
  ``serving_attr_tokens_total{phase}`` /
  ``serving_attr_seconds_total{phase}`` /
  ``serving_attr_prefill_work_tokens_total{kind}`` /
  ``serving_attr_spec_rejected_tokens_total`` plus the
  ``serving_useful_token_fraction`` / ``serving_prefix_prefill_
  saved_fraction`` / ``serving_model_flops_per_second`` /
  ``serving_mfu_fraction`` gauges — fed from ``on_quantum`` /
  ``on_spec_round`` / ``on_cached_prefill`` at the same boundaries.
- time series (host ring buffers, not prometheus):
  :meth:`timeseries` — ``tokens_per_s`` and ``spec_acceptance_rate``
  points for offline plots, plus the PER-REQUEST sample series the SLO
  layer's burn-rate windows evaluate (obs/slo.py): ``ttft_seconds``,
  ``e2e_latency_seconds``, ``inter_token_seconds`` as ``(t, value)``
  points, and ``request_outcomes`` as ``(t, bad)`` where bad is 1.0
  for a shed/error outcome and 0.0 for eos/length.
"""
from __future__ import annotations

import time
from collections import deque
from collections.abc import MutableMapping

from .attribution import CostLedger
from .registry import LATENCY_BUCKETS, MetricsRegistry
from .trace import TraceRecorder

__all__ = ["ServingObs"]

# legacy ServingEngine.stats key -> registry counter name, in the
# historical dict order (engine_stats()'s shape is part of the API)
_LEGACY_KEYS = {
    "steps": "serving_steps_total",
    "mixed_steps": "serving_mixed_steps_total",
    "decode_quanta": "serving_decode_quanta_total",
    "quantum_tokens": "serving_quantum_tokens_total",
    "prefill_tokens": "serving_prefill_tokens_total",
    "generated_tokens": "serving_generated_tokens_total",
    "occupancy_sum": "serving_occupancy_sum",
    "spec_rounds": "serving_spec_rounds_total",
    "spec_proposed": "serving_spec_proposed_total",
    "spec_accepted": "serving_spec_accepted_total",
}
_FLOAT_KEYS = ("occupancy_sum",)


class _LegacyStatsView(MutableMapping):
    """``engine.stats`` compatibility: same keys, same int/float types,
    same iteration order — but every read/write goes through the
    registry counters, so there is exactly ONE source of truth."""

    def __init__(self, counters):
        self._counters = counters  # legacy key -> Counter

    def __getitem__(self, key):
        v = self._counters[key].value()
        return v if key in _FLOAT_KEYS else int(v)

    def __setitem__(self, key, value):
        self._counters[key]._set(value)

    def __delitem__(self, key):
        raise TypeError("engine.stats has a fixed key set")

    def __iter__(self):
        return iter(self._counters)

    def __len__(self):
        return len(self._counters)

    def __repr__(self):
        return repr(dict(self))


class ServingObs:
    """Metrics + tracing sink for one :class:`ServingEngine`.

    Args:
        registry: share a registry across engines (default: fresh).
        trace: record Chrome trace events (bounded buffer; off by
            default — the metrics registry alone is always on).
        tracer: bring your own :class:`TraceRecorder` (wins over
            ``trace``).
        enabled: ``False`` short-circuits every rich hook (histograms,
            gauges, tracer, time series) — the ``obs="off"`` arm of the
            ``serving_obs_overhead`` bench; the legacy stats counters
            keep working either way.
        window_s: trailing window for the tokens/s gauge.
    """

    def __init__(self, registry=None, trace=False, tracer=None,
                 enabled=True, window_s=1.0, series_maxlen=4096):
        self.enabled = bool(enabled)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.tracer = tracer if tracer is not None \
            else (TraceRecorder() if trace else None)
        self.window_s = float(window_s)
        r = self.registry
        self._legacy = {
            key: r.counter(name, f"legacy engine.stats[{key!r}]")
            for key, name in _LEGACY_KEYS.items()
        }
        self._c_submitted = r.counter(
            "serving_requests_submitted_total", "requests queued")
        self._c_admitted = r.counter(
            "serving_requests_admitted_total", "requests given a slot")
        self._c_finished = r.counter(
            "serving_requests_finished_total", "requests retired")
        self._c_tokens = r.counter(
            "serving_tokens_emitted_total",
            "tokens appended to request streams")
        self._h_queue = r.histogram(
            "serving_queue_wait_seconds", "submit -> admit",
            buckets=LATENCY_BUCKETS)
        self._h_ttft = r.histogram(
            "serving_ttft_seconds",
            "submit -> first generated token (once per request)",
            buckets=LATENCY_BUCKETS)
        self._h_e2e = r.histogram(
            "serving_e2e_latency_seconds", "submit -> retirement",
            buckets=LATENCY_BUCKETS)
        self._h_itl = r.histogram(
            "serving_inter_token_seconds",
            "per-request mean inter-token latency at retirement",
            buckets=LATENCY_BUCKETS)
        self._h_quantum = r.histogram(
            "serving_quantum_seconds",
            "one dispatch: mixed step / decode quantum / spec round",
            buckets=LATENCY_BUCKETS)
        self._g_rate = r.gauge(
            "serving_tokens_per_second_window",
            "generated tok/s over the trailing window")
        self._g_hostgap = r.gauge(
            "serving_host_gap_fraction",
            "host wall minus device wall over quantum wall at the "
            "decode dispatch boundary (the multi-quantum driver's "
            "headline: collapses as K grows)")
        self._g_accept = r.gauge(
            "serving_spec_acceptance_rate",
            "per-round accepted/proposed")
        self._g_slots = r.gauge(
            "serving_slots_occupied", "live slots this step")
        self._g_blocks = r.gauge(
            "serving_pool_blocks_in_use", "KV pool blocks allocated")
        self._g_free = r.gauge(
            "serving_pool_free_blocks", "KV pool free-list length")
        self._g_util = r.gauge(
            "serving_pool_utilization",
            "live tokens / allocated token capacity")
        # dtype-aware residency: actual bytes the allocated blocks pin
        # (pool itemsize x elements + the int8 pools' f32 scale rows),
        # labeled by the pool's kv dtype so a dashboard shows the int8
        # residency win directly against a float engine's line
        self._g_bytes = r.gauge(
            "serving_pool_bytes",
            "bytes pinned by allocated KV blocks (incl. scale pools), "
            "by pool and kv_dtype")
        self._g_chip_bytes = r.gauge(
            "serving_pool_per_chip_bytes",
            "per-chip bytes pinned by allocated KV blocks under TP")
        self._c_shed = r.counter(
            "serving_requests_shed_total",
            "requests refused by load shedding")
        # the front door's overload counters (serving/frontend.py):
        # preempt/resume pair up over a run, drains count graceful
        # stop-the-front-door events, recomputed tokens are the KV a
        # preemption dropped (re-prefilled on resume — the recompute-
        # on-resume debt)
        self._c_preempted = r.counter(
            "serving_requests_preempted_total",
            "live requests evicted under pool pressure")
        self._c_resumed = r.counter(
            "serving_requests_resumed_total",
            "preempted requests re-admitted (recompute-on-resume)")
        self._c_recomputed = r.counter(
            "serving_tokens_recomputed_total",
            "cached tokens dropped by preemption (re-prefilled on "
            "resume)")
        self._c_drains = r.counter(
            "serving_drains_total", "graceful drains started")
        # content-addressed prefix cache (engine prefix_cache=True):
        # the pool keeps monotonic counters on its hot path; on_step
        # carries their deltas into the registry, so the metrics cost
        # nothing inside the allocator
        self._c_pc_hits = r.counter(
            "serving_prefix_cache_hits_total",
            "full prompt blocks served from the prefix index")
        self._c_pc_misses = r.counter(
            "serving_prefix_cache_misses_total",
            "full prompt blocks that had to be prefilled")
        self._c_pc_cow = r.counter(
            "serving_prefix_cache_cow_copies_total",
            "copy-on-write copies (first write into a shared block)")
        self._c_pc_shared = r.counter(
            "serving_prefix_cache_shared_blocks_total",
            "block aliases the prefix index created at admission")
        self._g_pc_frac = r.gauge(
            "serving_prefix_cache_cached_block_fraction",
            "index-held blocks / blocks in use")
        # resilience tier (serving/faults.py + serving/resilience.py):
        # injected faults, dispatch retries, watchdog overruns, the
        # degradation ladder and quarantines, snapshot restores — all
        # host-boundary events the engine reports at step edges
        self._c_faults = r.counter(
            "serving_faults_injected_total",
            "faults the seeded injector fired, by site/kind")
        self._c_retries = r.counter(
            "serving_quantum_retries_total",
            "quantum dispatches retried after an injected fault")
        self._c_watchdog = r.counter(
            "serving_watchdog_trips_total",
            "quantum dispatches that overran the p99-derived deadline")
        self._g_degraded = r.gauge(
            "serving_degraded_mode",
            "1 while a degraded mode is active, by mode "
            "(spec_disabled|pool_rebuild)")
        self._c_degrades = r.counter(
            "serving_degrades_total",
            "degradation-ladder activations, by mode")
        self._c_pool_rebuilds = r.counter(
            "serving_pool_rebuilds_total",
            "pool accounting rebuilt from live block tables")
        self._c_quarantines = r.counter(
            "serving_quarantines_total",
            "poison requests error-finished / prefix subtrees dropped, "
            "by kind")
        self._c_restores = r.counter(
            "serving_restores_total",
            "engines rebuilt from a snapshot (crash recovery)")
        # per-quantum collective census (TP serving): bytes/op counts
        # the ONE jitted quantum moves over mesh collectives, read off
        # the compiled HLO at engine build (analysis/collectives.py).
        # A static property of the executable — set once, never from
        # runtime callbacks, so the hot path stays untouched
        self._g_coll_bytes = r.gauge(
            "serving_collective_bytes_total",
            "bytes one quantum dispatch moves over mesh collectives "
            "(compiled-HLO census at engine build; 0 when tp=1)")
        self._g_coll_count = r.gauge(
            "serving_collective_count_total",
            "mesh collective ops in one quantum dispatch, by kind")
        self.quantum_collectives = {}
        # (pool identity, counter attr) -> last value synced; keyed by
        # id() so engines sharing one registry don't cross-credit, and
        # kept OUT of reset() so a registry reset restarts the counters
        # from zero without replaying the pool's full history
        self._pc_marks = {}
        # per-token cost ledger (obs/attribution.py): phase-attributed
        # tokens/walls + useful-fraction / prefix-savings / MFU gauges,
        # fed from the SAME boundaries below — no new host callbacks,
        # and disabled with the rest of the rich hooks (obs="off")
        self.ledger = CostLedger(r)
        self._window = deque()
        self._cum_tokens = 0
        self._series = {
            "tokens_per_s": deque(maxlen=series_maxlen),
            "spec_acceptance_rate": deque(maxlen=series_maxlen),
            # per-request samples the SLO burn-rate windows read
            "ttft_seconds": deque(maxlen=series_maxlen),
            "e2e_latency_seconds": deque(maxlen=series_maxlen),
            "inter_token_seconds": deque(maxlen=series_maxlen),
            "request_outcomes": deque(maxlen=series_maxlen),
        }

    # the engine's single clock (the old code had six scattered
    # ``now = time.perf_counter()`` blocks)
    @staticmethod
    def now():
        return time.perf_counter()

    def legacy_stats_view(self):
        return _LegacyStatsView(self._legacy)

    def timeseries(self):
        """{"tokens_per_s": [(t, v), ...], "spec_acceptance_rate":
        [...], "ttft_seconds": [...], "e2e_latency_seconds": [...],
        "inter_token_seconds": [...], "request_outcomes": [...]} —
        host ring buffers for offline plotting and the SLO layer's
        burn-rate windows (obs/slo.py)."""
        return {k: list(v) for k, v in self._series.items()}

    def series_snapshot(self, now=None):
        """JSON-able dump of :meth:`timeseries` plus the clock stamp a
        later offline SLO evaluation anchors its windows to (the
        ``python -m paddle_tpu.obs slo --in`` format)."""
        return {
            "version": 1,
            "now": self.now() if now is None else float(now),
            "series": {k: [[float(t), float(v)] for t, v in pts]
                       for k, pts in self._series.items()},
        }

    def reset(self):
        """Return every surface to its initial state between bench
        warmup and timed phases: registry series
        (:meth:`MetricsRegistry.reset` — counters, gauges AND
        histograms), the throughput window, and the ring-buffer time
        series. Replaces the old per-key zeroing through
        ``engine.stats``."""
        self.registry.reset()
        self._window.clear()
        self._cum_tokens = 0
        for s in self._series.values():
            s.clear()

    # -- request lifecycle hooks -------------------------------------------
    def on_submit(self, req):
        if not self.enabled:
            return
        self._c_submitted.inc()
        if self.tracer is not None:
            self.tracer.thread_name(0, "engine")
            self.tracer.instant("submit", req.arrival_time, tid=0,
                                args={"req": str(req.req_id)})

    def on_admit(self, req, now):
        if not self.enabled:
            return
        self._c_admitted.inc()
        self._h_queue.observe(now - req.arrival_time)
        if self.tracer is not None:
            tid = req.slot + 1
            self.tracer.thread_name(tid, f"slot{req.slot}")
            self.tracer.instant("admit", now, tid=tid,
                                args={"req": str(req.req_id)})

    def on_first_token(self, req, now):
        """TTFT — the caller stamps ``first_token_time`` exactly once
        (at the prefill-completion step), so this observes once per
        request by construction."""
        if not self.enabled:
            return
        ttft = now - req.arrival_time
        self._h_ttft.observe(ttft)
        self._series["ttft_seconds"].append((now, ttft))
        if self.tracer is not None:
            self.tracer.instant("first_token", now, tid=req.slot + 1,
                                args={"req": str(req.req_id)})

    def on_token(self, req):
        """One token actually appended to a request's stream."""
        if self.enabled:
            self._c_tokens.inc()

    def on_retire(self, req, now):
        if not self.enabled:
            return
        self._c_finished.inc()
        e2e = now - req.arrival_time
        self._h_e2e.observe(e2e)
        self._series["e2e_latency_seconds"].append((now, e2e))
        # outcome sample for the error/shed-rate SLO: eos/stop/length
        # are the good endings, anything else is a bad one
        self._series["request_outcomes"].append(
            (now, 0.0 if req.finish_reason in ("eos", "stop", "length")
             else 1.0))
        n = len(req.tokens)
        if req.first_token_time is not None and n >= 2:
            itl = (req.finish_time - req.first_token_time) / (n - 1)
            self._h_itl.observe(itl)
            self._series["inter_token_seconds"].append((now, itl))
        if self.tracer is not None and req.slot is not None:
            self.tracer.complete(
                f"req {req.req_id}", req.admit_time or now, now,
                tid=req.slot + 1,
                args={"tokens": n, "reason": req.finish_reason,
                      "prompt_len": req.prompt_len})

    def on_shed(self, req, now):
        """A request refused admission by a load-shedding policy (the
        front door's SLO-driven admission, serving/policy.py): counted,
        and recorded as a BAD outcome sample so the error/shed-rate
        objective burns budget for it."""
        if not self.enabled:
            return
        self._c_shed.inc()
        self._series["request_outcomes"].append((now, 1.0))
        if self.tracer is not None:
            self.tracer.instant("shed", now, tid=0,
                                args={"req": str(req.req_id)})

    def on_preempt(self, req, now, cached_tokens=0):
        """A live request evicted under pool pressure: its
        ``cached_tokens`` of KV go back to the pool and become
        recompute debt (re-prefilled when it resumes)."""
        if not self.enabled:
            return
        self._c_preempted.inc()
        self._c_recomputed.inc(int(cached_tokens))
        if self.tracer is not None:
            tid = 0 if req.slot is None else req.slot + 1
            self.tracer.instant("preempt", now, tid=tid,
                                args={"req": str(req.req_id),
                                      "cached_tokens": int(
                                          cached_tokens)})

    def on_resume(self, req, now):
        """A preempted request re-admitted to a slot (the resume half
        of the preempt/resume pair; TTFT and queue-wait were observed
        on the FIRST admission, so neither re-observes here)."""
        if not self.enabled:
            return
        self._c_resumed.inc()
        if self.tracer is not None:
            tid = 0 if req.slot is None else req.slot + 1
            self.tracer.instant("resume", now, tid=tid,
                                args={"req": str(req.req_id),
                                      "preemptions": int(
                                          req.preemptions)})

    def on_drain(self, now, live=0, waiting=0):
        """The front door stopped admitting (graceful drain): counted;
        in-flight work finishes and the flight recorder flushes."""
        if not self.enabled:
            return
        self._c_drains.inc()
        if self.tracer is not None:
            self.tracer.instant("drain", now, tid=0,
                                args={"live": int(live),
                                      "waiting": int(waiting)})

    # -- step / dispatch hooks ---------------------------------------------
    def on_step(self, now, live, num_slots, pool, d_pool=None):
        """Per-scheduler-iteration gauges (slot occupancy + pool
        health); also feeds the trace's counter tracks."""
        if not self.enabled:
            return
        self._g_slots.set(live)
        pools = [("target", pool)]
        if d_pool is not None:
            pools.append(("draft", d_pool))
        for label, p in pools:
            st = p.fragmentation_stats()
            self._g_blocks.set(st["blocks_in_use"], pool=label)
            self._g_free.set(st["free_blocks"], pool=label)
            self._g_util.set(st["utilization"], pool=label)
            kv_dtype = st.get("kv_dtype", "float")
            self._g_bytes.set(float(st.get("bytes_in_use", 0)),
                              pool=label, kv_dtype=kv_dtype)
            self._g_chip_bytes.set(
                float(st.get("per_chip_bytes_in_use", 0)),
                pool=label, kv_dtype=kv_dtype)
            if getattr(p, "prefix_cache_enabled", False):
                self._sync_prefix(label, p, st)
        if self.tracer is not None:
            self.tracer.counter(
                "occupancy", now,
                {"live_slots": live, "free_slots": num_slots - live})
            self.tracer.counter(
                "pool_blocks", now,
                {label: p.blocks_in_use for label, p in pools})

    def _sync_prefix(self, label, pool, st):
        """Carry one pool's monotonic prefix-cache counters into the
        registry as DELTAS since the last step, and refresh the
        cached-block-fraction gauge."""
        for attr, c in (("prefix_hits", self._c_pc_hits),
                        ("prefix_misses", self._c_pc_misses),
                        ("cow_copies", self._c_pc_cow),
                        ("prefix_aliases", self._c_pc_shared)):
            v = getattr(pool, attr)
            key = (id(pool), attr)
            delta = v - self._pc_marks.get(key, 0)
            if delta:
                c.inc(delta, pool=label)
            self._pc_marks[key] = v
        in_use = st["blocks_in_use"]
        self._g_pc_frac.set(
            (st["cached_blocks"] / in_use) if in_use else 0.0,
            pool=label)

    def set_quantum_collectives(self, info):
        """Publish the engine-build collective census: ``info`` is the
        engine's ``quantum_collectives`` dict (``tp``, ``count_total``,
        ``bytes_total``, per-kind ``by_kind``). Called once at engine
        construction — the census is a property of the compiled
        executable, so the gauges never move after build. The totals
        are published unlabeled and the per-kind split under
        ``{kind=all-reduce|all-gather|...}`` on the same two gauges."""
        self.quantum_collectives = dict(info or {})
        if not self.enabled:
            return
        info = self.quantum_collectives
        self._g_coll_bytes.set(float(info.get("bytes_total", 0)))
        self._g_coll_count.set(float(info.get("count_total", 0)))
        for kind, d in (info.get("by_kind") or {}).items():
            self._g_coll_bytes.set(float(d["bytes"]), kind=kind)
            self._g_coll_count.set(float(d["count"]), kind=kind)

    def on_quantum(self, kind, t0, t1, tokens, rows, breakdown=None,
                   device_s=None):
        """One dispatch boundary: ``kind`` is ``mixed`` (chunked
        prefill + decode rows through block_mha), ``decode`` (the
        jitted quantum) or ``spec_round``; ``tokens`` is how many
        tokens the dispatch appended to request streams. A mixed step
        passes ``breakdown`` (prefill/decode emission split + novel vs
        recompute work tokens) for the cost ledger's phase
        attribution. ``device_s`` (decode quanta) is the measured
        device-side share of this quantum's wall — dispatch-return to
        sync-complete, the same decomposition analysis.cost's
        ``host_gap_seconds`` estimates statically — and refreshes the
        ``serving_host_gap_fraction`` gauge (this module never imports
        jax, so the split is measured by the engine and handed in)."""
        if not self.enabled:
            return
        wall = t1 - t0
        if device_s is not None and wall > 0.0:
            self._g_hostgap.set(max(wall - device_s, 0.0) / wall)
        self._h_quantum.observe(t1 - t0, kind=kind)
        self._cum_tokens += int(tokens)
        self._window.append((t1, self._cum_tokens))
        while len(self._window) > 2 \
                and t1 - self._window[0][0] > self.window_s:
            self._window.popleft()
        t_old, c_old = self._window[0]
        if t1 > t_old:
            rate = (self._cum_tokens - c_old) / (t1 - t_old)
            self._g_rate.set(rate)
            self._series["tokens_per_s"].append((t1, rate))
        self.ledger.on_quantum(kind, t0, t1, tokens,
                               breakdown=breakdown,
                               window_rate=self._g_rate.value())
        if self.tracer is not None:
            self.tracer.complete(kind, t0, t1, tid=0,
                                 args={"tokens": int(tokens),
                                       "rows": int(rows)})
            self.tracer.counter("tokens_per_s", t1,
                                {"window": self._g_rate.value()})

    def on_spec_round(self, now, proposed, accepted):
        if not self.enabled or proposed <= 0:
            return
        self.ledger.on_spec_round(proposed, accepted)
        rate = accepted / proposed
        self._g_accept.set(rate)
        self._series["spec_acceptance_rate"].append((now, rate))

    # -- resilience hooks --------------------------------------------------
    def on_fault(self, site, kind):
        """One injected fault fired (synced from the injector's journal
        at the step boundary — the injector itself never touches the
        registry)."""
        if self.enabled:
            self._c_faults.inc(site=site, kind=kind)

    def on_retry(self, kind, attempt):
        """One dispatch retried after an injected fault (``attempt`` is
        the 1-based retry number; only the count is exported)."""
        if self.enabled:
            self._c_retries.inc(kind=kind)

    def on_watchdog(self, kind, elapsed):
        """One quantum overran its watchdog deadline (detection-only:
        the dispatch already returned)."""
        if not self.enabled:
            return
        self._c_watchdog.inc(kind=kind)
        if self.tracer is not None:
            self.tracer.instant("watchdog_trip", self.now(), tid=0,
                                args={"kind": kind,
                                      "elapsed_s": float(elapsed)})

    def on_degrade(self, mode, now):
        """A degradation-ladder rung activated (``spec_disabled`` |
        ``pool_rebuild``): the mode gauge latches 1 and the activation
        counter bumps; pool rebuilds also feed their own counter."""
        if not self.enabled:
            return
        self._g_degraded.set(1.0, mode=mode)
        self._c_degrades.inc(mode=mode)
        if mode == "pool_rebuild":
            self._c_pool_rebuilds.inc()
        if self.tracer is not None:
            self.tracer.instant("degrade", now, tid=0,
                                args={"mode": mode})

    def on_quarantine(self, now, what, count=1):
        """``what="poison"``: a poison request was isolated by batch
        bisect and error-finished. ``what="prefix"``: cached prefix
        entries dropped after a content-verify mismatch."""
        if not self.enabled:
            return
        self._c_quarantines.inc(int(count), kind=what)
        if self.tracer is not None:
            self.tracer.instant("quarantine", now, tid=0,
                                args={"kind": what,
                                      "count": int(count)})

    def on_restore(self, now, inflight):
        """An engine was rebuilt from a snapshot, re-admitting
        ``inflight`` requests via recompute-on-resume."""
        if not self.enabled:
            return
        self._c_restores.inc()
        if self.tracer is not None:
            self.tracer.instant("restore", now, tid=0,
                                args={"inflight": int(inflight)})

    def on_cached_prefill(self, req, tokens):
        """Prompt tokens an admission skipped via a prefix-cache alias
        — the savings side of the ledger's prefill work split (fires
        at the existing ``_admit`` boundary)."""
        if not self.enabled:
            return
        self.ledger.on_cached_prefill(tokens)
