"""Observability CLI::

    python -m paddle_tpu.obs snapshot --in metrics.json [--format prom]
    python -m paddle_tpu.obs snapshot --demo [--format prom|json]
    python -m paddle_tpu.obs export --demo --out trace.json \
        [--metrics-out metrics.json] [--spec]
    python -m paddle_tpu.obs export --in trace.json      # validate
    python -m paddle_tpu.obs serve --demo [--port 9100] [--duration S]
    python -m paddle_tpu.obs slo --demo [--out series.json]
    python -m paddle_tpu.obs slo --in series.json [--fail-on critical]
    python -m paddle_tpu.obs watch --url http://127.0.0.1:9100
    python -m paddle_tpu.obs watch --in metrics.json [--slo-in rep.json]
    python -m paddle_tpu.obs check                       # CI gate

``snapshot`` renders a metrics snapshot (live from the ``--demo``
engine run, or re-rendered offline from a saved ``--in`` JSON dump) as
Prometheus text or stable-sorted JSON. ``export`` writes/validates the
Chrome trace-event JSON (open in Perfetto / chrome://tracing); with
``--demo`` it drives a tiny CPU serving engine (``--spec`` switches it
to the speculative arm) so the artifact carries real request spans.

The operability tier (ISSUE 6): ``serve`` runs the live HTTP exporter
(obs/export.py — ``/metrics`` ``/healthz`` ``/slo`` ``/snapshot``
``/anomalies``) over the demo engine; ``slo`` evaluates the burn-rate
health report (live from ``--demo``, or offline from a saved
``series_snapshot`` via ``--in``; ``--fail-on warn|critical`` turns
the state into an exit code for scripts); ``watch`` renders the
terminal dashboard — polling a running exporter's ``/snapshot`` +
``/slo`` with ``--url``, or one frame from saved files with ``--in``.

``check`` is the instrumentation-can't-change-the-graph gate used by
``scripts/check_graphs.sh``: it builds the serving + speculative +
front-door + prefix-cache analysis recipes — whose engines run with
FULL observability (registry + tracer + SLOs + flight recorder) —
re-checks their budgets, compares the golden fingerprints, and asserts
the instrumentation actually recorded (metrics counted, trace
validates). It then runs the SLO smoke on the demo engine (lenient
objectives must read ``ok``, impossible ones ``critical``, forced
threshold crossings must produce schema-valid anomaly journals), the
FRONT-DOOR smoke (ISSUE 7: a forced priority preemption must fire the
preempted/resumed/recomputed counters, resume bit-continuously, drain
must flush the flight journals, and the dashboard must render the
overload line), and the PREFIX-CACHE smoke (ISSUE 9: a forced cache
hit + copy-on-write must fire the prefix counters, keep the streams
bit-identical to an unshared engine, and render the dashboard's
prefix line), the QUANTIZED-SERVING smoke (ISSUE 14: a forced hit +
COW on a weight-int8/kv-int8 engine must keep shared streams
bit-identical to an unshared int8 engine and show the dtype-aware
pool-bytes gauge well under half a float engine's), and the
ATTRIBUTION smoke (ISSUE 10: the cost ledger
must conserve — phase token buckets sum to the emitted-token counter
token-for-token, and per-phase seconds sum to the measured quantum
walls within float tolerance), and the RESILIENCE smoke (ISSUE 13: a
bounded seeded chaos soak — faults x preemption x COW — must keep
every non-poisoned stream bit-exact vs the fault-free arm with zero
leaked blocks), and the CLUSTER smoke (ISSUE 15: a 2-replica router
run on a shared-prefix trace must land affinity hits, fire the
``serving_router_*`` counters, stream bit-identically to a
cluster-of-1, and render the merged dashboard's cluster line). Exit
non-zero on drift.
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def _demo_engine(spec=False, trace=True, slo=None, flight=None):
    """A tiny CPU serving run with full instrumentation: a handful of
    ragged requests through prefill/decode (+ the speculative arm),
    enough to populate every serving metric and trace track."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine

    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    kw = {}
    if spec:
        paddle.seed(7)
        kw = dict(
            spec_draft=LlamaForCausalLM(LlamaConfig.tiny(
                tensor_parallel=False, num_hidden_layers=1)),
            spec_gamma=2)
    engine = ServingEngine(model, num_slots=3, block_size=4,
                           prefill_chunk=4, decode_quantum=3,
                           trace=trace, slo=slo, flight=flight, **kw)
    rng = np.random.RandomState(0)
    for n, mn in ((5, 6), (9, 4), (3, 8), (12, 5)):
        engine.submit(rng.randint(1, cfg.vocab_size, n)
                      .astype(np.int32), max_new_tokens=mn)
    engine.run()
    return engine


def _cmd_snapshot(args):
    from .registry import prometheus_from_snapshot

    if args.demo:
        snap = _demo_engine(spec=args.spec,
                            trace=False).obs.registry.snapshot()
    elif args.infile:
        with open(args.infile) as f:
            snap = json.load(f)
    else:
        print("snapshot: need --demo or --in FILE", file=sys.stderr)
        return 2
    text = (prometheus_from_snapshot(snap) if args.format == "prom"
            else json.dumps(snap, indent=2, sort_keys=True) + "\n")
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0


def _cmd_export(args):
    from .trace import load_chrome_trace

    if args.demo:
        if not args.out:
            print("export --demo: need --out FILE", file=sys.stderr)
            return 2
        engine = _demo_engine(spec=args.spec, trace=True)
        engine.obs.tracer.save(args.out)
        n = len(engine.obs.tracer.events)
        print(f"wrote {args.out}: {n} trace events "
              f"({engine.obs.tracer.dropped} dropped)", file=sys.stderr)
        if args.metrics_out:
            with open(args.metrics_out, "w") as f:
                f.write(engine.obs.registry.snapshot_json(indent=2))
            print(f"wrote {args.metrics_out}", file=sys.stderr)
        return 0
    if args.infile:
        obj = load_chrome_trace(args.infile)
        print(f"{args.infile}: valid chrome trace, "
              f"{len(obj['traceEvents'])} events", file=sys.stderr)
        return 0
    print("export: need --demo or --in FILE", file=sys.stderr)
    return 2


def _cmd_serve(args):
    """Live exporter over the demo engine: the zero-to-scrape path —
    run it, point a browser / curl / Prometheus at the printed URLs."""
    from .export import MetricsExporter

    engine = _demo_engine(spec=args.spec, trace=False, slo=True,
                          flight=True)
    exporter = MetricsExporter.for_engine(
        engine, host=args.host, port=args.port).start()
    for route in exporter.routes():
        print(f"serving {exporter.url(route)}", file=sys.stderr)
    try:
        if args.duration is not None:
            time.sleep(args.duration)
        else:
            print("Ctrl-C to stop", file=sys.stderr)
            while True:
                time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        exporter.stop()
    return 0


def _cmd_slo(args):
    """Burn-rate health report: live from the demo engine, or offline
    from a saved ``ServingObs.series_snapshot()`` dump."""
    from .slo import SLOSet, state_of

    if args.demo:
        engine = _demo_engine(spec=args.spec, trace=False, slo=True,
                              flight=True)
        report = engine.health()
        if args.out:
            with open(args.out, "w") as f:
                json.dump(engine.obs.series_snapshot(), f,
                          sort_keys=True)
            print(f"wrote {args.out}", file=sys.stderr)
    elif args.infile:
        with open(args.infile) as f:
            snap = json.load(f)
        if snap.get("version") != 1 or "series" not in snap:
            print(f"slo: {args.infile} is not a series snapshot "
                  f"(need version=1 + 'series'; write one with "
                  f"`slo --demo --out FILE`)", file=sys.stderr)
            return 2
        report = SLOSet().evaluate(snap["series"], now=snap.get("now"))
    else:
        print("slo: need --demo or --in FILE", file=sys.stderr)
        return 2
    print(json.dumps(report, indent=2, sort_keys=True))
    if args.fail_on and state_of(report["state"]) >= args.fail_on:
        print(f"slo: state {report['state']} >= --fail-on "
              f"{args.fail_on}", file=sys.stderr)
        return 1
    return 0


def _cmd_watch(args):
    """Terminal dashboard: poll a live exporter (``--url``) or render
    one frame from saved snapshot/report files (``--in``)."""
    from .export import render_dashboard

    def frame():
        if args.url:
            from urllib.request import urlopen

            base = args.url.rstrip("/")
            with urlopen(base + "/snapshot") as r:
                snap = json.load(r)
            with urlopen(base + "/slo") as r:
                report = json.load(r)
            return snap, report
        with open(args.infile) as f:
            snap = json.load(f)
        report = None
        if args.slo_in:
            with open(args.slo_in) as f:
                report = json.load(f)
        return snap, report

    if not args.url and not args.infile:
        print("watch: need --url BASE or --in metrics.json",
              file=sys.stderr)
        return 2
    frames = args.frames if args.frames is not None \
        else (0 if args.url else 1)  # 0 == until interrupted
    n = 0
    try:
        while True:
            snap, report = frame()
            if n and args.url:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear between polls
            sys.stdout.write(render_dashboard(snap, report))
            sys.stdout.flush()
            n += 1
            if frames and n >= frames:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


_CHECK_RECIPES = ("serving_decode_step", "speculative_verify_step",
                  "serving_frontdoor_step", "serving_prefix_step",
                  "serving_int8_step", "serving_tp_step",
                  "serving_multiquantum_step")

_REEXEC_GUARD = "_PADDLE_TPU_OBS_REEXEC"


def _ensure_check_devices(argv, need=8):
    """``check`` now audits the tp=2 serving recipe, which needs a
    multi-device mesh; on a 1-device host platform, re-exec with the
    virtual-device flag set before jax initializes (the same conftest
    trick analysis/__main__.py uses). Inert when enough devices are
    already visible."""
    import os

    import jax

    if jax.device_count() >= need or os.environ.get(_REEXEC_GUARD):
        return
    flag = f"--xla_force_host_platform_device_count={need}"
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()
    env[_REEXEC_GUARD] = "1"
    cmd = [sys.executable, "-m", "paddle_tpu.obs"] + list(
        argv if argv is not None else sys.argv[1:])
    os.execve(sys.executable, cmd, env)


def _check_slo_smoke():
    """The operability-tier smoke `check` appends to the fingerprint
    gate: drive the demo engine with SLOs + a flight recorder whose
    triggers are impossible to satisfy, then assert the burn-rate
    evaluation orders states correctly on BOTH sides of a threshold
    and every forced crossing produced a schema-valid journal."""
    from .flight import FlightRecorder
    from .slo import SLOSet, default_serving_slos

    engine = _demo_engine(
        trace=False, slo=True,
        flight=FlightRecorder(ttft_threshold=1e-9, e2e_threshold=1e-9))
    finished = len(engine.completed)
    lenient = SLOSet(default_serving_slos(
        ttft_p95_s=1e9, inter_token_p99_s=1e9, e2e_p99_s=1e9))
    tight = SLOSet(default_serving_slos(
        ttft_p95_s=1e-9, inter_token_p99_s=1e-9, e2e_p99_s=1e-9))
    ok = lenient.evaluate(engine.obs)["state"]
    crit = tight.evaluate(engine.obs)["state"]
    if ok != "ok":
        raise AssertionError(
            f"lenient SLOs read {ok!r}, expected 'ok'")
    if crit != "critical":
        raise AssertionError(
            f"impossible SLOs read {crit!r}, expected 'critical'")
    records = engine.flight.records()  # schema-validates
    if len(records) != finished:
        raise AssertionError(
            f"{len(records)} anomaly journals for {finished} forced "
            f"threshold crossings")
    report = engine.health()  # stock objectives, real state
    print(f"slo smoke: lenient=ok impossible=critical "
          f"stock={report['state']}, {len(records)} schema-valid "
          f"anomaly journals for {finished} requests")


def _check_frontdoor_smoke():
    """The front-door smoke (ISSUE 7): drive a one-slot engine through
    a FORCED preemption — a BATCH request mid-decode evicted by an
    INTERACTIVE arrival — then assert the overload counters fired
    (preempted/resumed/recomputed + a drain), the resumed stream is
    the right length, the pool fully reclaimed its blocks, and the
    dashboard frame renders the overload line from a live snapshot."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (
        BATCH, INTERACTIVE, FrontDoorPolicy, ServingEngine,
        ServingFrontDoor,
    )
    from .export import render_dashboard

    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    engine = ServingEngine(model, num_slots=1, block_size=4,
                           prefill_chunk=4, decode_quantum=2,
                           slo=True, flight=True)
    door = ServingFrontDoor(engine, policy=FrontDoorPolicy())
    rng = np.random.RandomState(0)
    low = door.submit(rng.randint(1, cfg.vocab_size, 5)
                      .astype(np.int32), max_new_tokens=6,
                      priority=BATCH)
    while len(low.request.tokens) < 2:  # batch request mid-decode
        door.pump()
    hi = door.submit(rng.randint(1, cfg.vocab_size, 4)
                     .astype(np.int32), max_new_tokens=4,
                     priority=INTERACTIVE)
    summary = door.drain()  # finish everything, flush the recorder
    reg = engine.obs.registry
    if reg.get("serving_requests_preempted_total").value() < 1 \
            or reg.get("serving_requests_resumed_total").value() < 1:
        raise AssertionError(
            "forced preemption did not fire: "
            f"{summary}")
    if reg.get("serving_tokens_recomputed_total").value() < 1:
        raise AssertionError("preemption recorded no recompute debt")
    if reg.get("serving_drains_total").value() != 1:
        raise AssertionError("drain counter did not fire")
    if len(hi.request.tokens) != 4 or len(low.request.tokens) != 6:
        raise AssertionError(
            f"streams wrong after preempt/resume: hi="
            f"{len(hi.request.tokens)} low={len(low.request.tokens)}")
    if engine.pool.fragmentation_stats()["blocks_in_use"] != 1:
        raise AssertionError("pool leaked blocks across preemption")
    frame = render_dashboard(reg.snapshot(), engine.health())
    if "preempted" not in frame or "shed" not in frame:
        raise AssertionError("dashboard frame missing overload line")
    print(f"front-door smoke: preempted="
          f"{engine.scheduler.preempted_total} resumed="
          f"{engine.scheduler.resumed_total} recomputed="
          f"{int(reg.get('serving_tokens_recomputed_total').value())} "
          f"tokens, drain flushed "
          f"{summary['flight']['captured_total']} journals")


def _check_prefix_smoke():
    """The prefix-cache smoke (ISSUE 9): force a cache hit and a
    copy-on-write on a tiny engine — one request publishes its prompt
    blocks, an identical prompt aliases them (capped one token short,
    so its re-prefill COWs the tail block) — then assert the registry
    counters fired, the streams are bit-identical to an UNSHARED
    engine's, the per-request cached-token count surfaced, pool
    accounting stayed sane (utilization <= 1 with sharing live), and
    the dashboard renders the prefix line."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine
    from .export import render_dashboard
    from .flight import FlightRecorder

    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, cfg.vocab_size, 8).astype(np.int32)

    def drive(prefix):
        engine = ServingEngine(model, num_slots=2, block_size=4,
                               prefill_chunk=8, decode_quantum=2,
                               prefix_cache=prefix, slo=True,
                               # impossible thresholds: every journal
                               # captures, so the admit events (with
                               # their cached/novel block counts) stay
                               # inspectable after retirement
                               flight=FlightRecorder(
                                   ttft_threshold=1e-9,
                                   e2e_threshold=1e-9))
        first = engine.submit(prompt.copy(), max_new_tokens=4)
        engine.step()  # prefill + publish before the twin arrives
        second = engine.submit(prompt.copy(), max_new_tokens=4)
        engine.run()
        return engine, first, second

    plain, p1, p2 = drive(False)
    cached, c1, c2 = drive(True)
    if (p1.tokens, p2.tokens) != (c1.tokens, c2.tokens):
        raise AssertionError(
            f"prefix-cached streams diverged: {c1.tokens}/{c2.tokens} "
            f"vs unshared {p1.tokens}/{p2.tokens}")
    if c2.cached_prefix_tokens != 8:
        raise AssertionError(
            f"twin aliased {c2.cached_prefix_tokens} tokens, "
            f"expected its full 8-token prompt")
    pool = cached.pool
    if pool.prefix_hits < 2 or pool.cow_copies < 1:
        raise AssertionError(
            f"forced hit/COW did not fire: hits={pool.prefix_hits} "
            f"cow={pool.cow_copies}")
    reg = cached.obs.registry
    for m in ("serving_prefix_cache_hits_total",
              "serving_prefix_cache_cow_copies_total",
              "serving_prefix_cache_shared_blocks_total"):
        if reg.get(m).value(pool="target") < 1:
            raise AssertionError(f"registry counter {m} never fired")
    st = pool.fragmentation_stats()
    if st["utilization"] > 1.0:
        raise AssertionError(
            f"refcount-aware utilization broke: {st}")
    frame = render_dashboard(reg.snapshot())
    if "prefix[" not in frame:
        raise AssertionError("dashboard frame missing prefix line")
    admits = [e for j in cached.flight.records()
              for e in j["events"] if e["kind"] == "admit"]
    if not any(e.get("cached_blocks") for e in admits):
        raise AssertionError(
            "flight admit events carry no cached-block counts")
    print(f"prefix smoke: hits={pool.prefix_hits} "
          f"misses={pool.prefix_misses} cow={pool.cow_copies} "
          f"cached_blocks={pool.cached_blocks}, streams bit-identical "
          f"to the unshared engine")


def _check_int8_smoke():
    """The quantized-serving smoke (ISSUE 14): force a prefix-cache
    hit and a copy-on-write on an int8 engine (weight-only int8 +
    int8 KV with per-row scale pools) and assert sharing composes
    with quantization — the shared streams stay bit-identical to an
    UNSHARED int8 engine's, the hit/COW counters fire on the
    quantized pool, and the dtype-aware ``serving_pool_bytes`` gauge
    shows the int8 pool pinning well under half the bytes of a float
    engine holding the same blocks."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine

    cfg = LlamaConfig.tiny(tensor_parallel=False)
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, cfg.vocab_size, 8).astype(np.int32)

    def drive(prefix, quant):
        # a fresh model per engine: the quantize sweep rewrites the
        # Linear layers in place, so engines must not share one
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        kw = (dict(quantize="weight_only_int8", kv_dtype="int8")
              if quant else {})
        engine = ServingEngine(model, num_slots=2, block_size=4,
                               prefill_chunk=8, decode_quantum=2,
                               prefix_cache=prefix, **kw)
        first = engine.submit(prompt.copy(), max_new_tokens=4)
        engine.step()  # prefill + publish before the twin arrives
        mid_bytes = engine.pool.bytes_in_use()
        second = engine.submit(prompt.copy(), max_new_tokens=4)
        engine.run()
        return engine, first, second, mid_bytes

    shared, s1, s2, q_bytes = drive(True, True)
    plain, p1, p2, _ = drive(False, True)
    flt, _, _, f_bytes = drive(True, False)
    if (s1.tokens, s2.tokens) != (p1.tokens, p2.tokens):
        raise AssertionError(
            f"int8 prefix-shared streams diverged from the unshared "
            f"int8 engine: {s1.tokens}/{s2.tokens} vs "
            f"{p1.tokens}/{p2.tokens}")
    pool = shared.pool
    if not pool.quantized:
        raise AssertionError("kv_dtype='int8' engine built a float "
                             "pool")
    if pool.prefix_hits < 2 or pool.cow_copies < 1:
        raise AssertionError(
            f"forced hit/COW did not fire on the int8 pool: "
            f"hits={pool.prefix_hits} cow={pool.cow_copies}")
    if not q_bytes or q_bytes > 0.5 * f_bytes:
        raise AssertionError(
            f"int8 pool residency win missing: {q_bytes} B vs float "
            f"{f_bytes} B for the same allocated blocks")
    g = shared.obs.registry.get("serving_pool_bytes")
    if g.value(pool="target", kv_dtype="int8") <= 0:
        raise AssertionError(
            "serving_pool_bytes{kv_dtype=int8} gauge never fired "
            "(the prefix index holds cached blocks, so the final "
            "step's residency must be non-zero)")
    print(f"int8 smoke: hits={pool.prefix_hits} "
          f"cow={pool.cow_copies}, shared streams bit-identical to "
          f"the unshared int8 engine, pool bytes {q_bytes} vs float "
          f"{f_bytes} ({f_bytes / q_bytes:.2f}x residency win)")


def _check_attribution_smoke():
    """The cost-ledger smoke (ISSUE 10): drive the demo engine through
    its speculative arm and assert the ledger is CONSERVATIVE — every
    emitted token lands in exactly one phase bucket (ledger totals ==
    the legacy registry counters token-for-token), prefill work
    decomposes into novel + recompute, spec-verify waste equals
    proposed − accepted, and the per-phase wall seconds sum back to
    the measured quantum walls within float tolerance."""
    engine = _demo_engine(spec=True)
    reg = engine.obs.registry
    ledger = engine.obs.ledger

    emitted = ledger.emitted_tokens()
    total_emitted = reg.get("serving_tokens_emitted_total").value()
    if sum(emitted.values()) != total_emitted:
        raise AssertionError(
            f"ledger lost tokens: phase buckets {emitted} sum to "
            f"{sum(emitted.values())}, engine emitted {total_emitted}")
    work = ledger.prefill_work()
    prefill_total = reg.get("serving_prefill_tokens_total").value()
    if work["novel"] + work["recompute"] != prefill_total:
        raise AssertionError(
            f"prefill work {work} does not decompose the legacy "
            f"counter {prefill_total}")
    proposed = reg.get("serving_spec_proposed_total").value()
    accepted = reg.get("serving_spec_accepted_total").value()
    if proposed <= 0 or emitted["spec_verify"] <= 0:
        raise AssertionError(
            f"spec arm never exercised: proposed={proposed} "
            f"spec_verify emitted={emitted['spec_verify']}")
    rejected = ledger.waste_tokens()["spec_rejected"]
    if rejected != proposed - accepted:
        raise AssertionError(
            f"spec waste drifted: ledger rejected={rejected}, "
            f"engine proposed-accepted={proposed - accepted}")
    hist = reg.get("serving_quantum_seconds")
    wall = sum(hist.sum(kind=k) for k in ("mixed", "decode",
                                          "spec_round"))
    attributed = sum(ledger.phase_seconds().values())
    if abs(attributed - wall) > 1e-6 * max(1.0, wall):
        raise AssertionError(
            f"phase seconds {attributed:.9f} do not sum to measured "
            f"quantum wall {wall:.9f}")
    rep = engine.attribution()
    if not 0.0 < rep["useful_token_fraction"] <= 1.0:
        raise AssertionError(
            f"useful-token fraction out of range: {rep}")
    if rep["mfu"]["flops_per_token"] <= 0:
        raise AssertionError(
            f"ledger never configured with model FLOPs: {rep['mfu']}")
    print(f"attribution smoke: {int(total_emitted)} tokens conserved "
          f"across {emitted}, useful="
          f"{rep['useful_token_fraction']:.3f}, "
          f"{attributed:.3f}s attributed == quantum wall")


def _check_resilience_smoke():
    """The chaos-soak smoke (ISSUE 13): a bounded seeded run of the
    two-arm resilience soak — same workload fault-free and under an
    armed injector + seeded preemptions — asserting faults actually
    fired and every non-poisoned stream stayed bit-exact. run_soak
    hard-asserts drain, definite finish reasons and zero leaked blocks
    internally; replay any failure from the printed seed alone."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from ..serving.soak import run_soak

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False))
    model.eval()
    # 6 rounds keeps the smoke under the eager mixed-prefill budget
    # (~30 s on CPU) while still landing a couple of injected faults
    rep = run_soak(model, rounds=6, seed=2)
    if rep["faults_injected"] < 1:
        raise AssertionError(
            f"soak injected no faults — plan/seed drifted: {rep}")
    if rep["requests"] < 1:
        raise AssertionError(f"soak submitted nothing: {rep}")
    expect_exact = rep["requests"] - len(rep["poisoned"])
    if rep["bitexact_streams"] != expect_exact:
        raise AssertionError(
            f"soak lost streams: {rep['bitexact_streams']} bit-exact "
            f"of {expect_exact} non-poisoned")
    print(f"resilience smoke: seed={rep['seed']} "
          f"rounds={rep['rounds']} requests={rep['requests']} "
          f"faults={rep['faults_injected']} "
          f"retries={rep['retries']} skips={rep['step_skips']}, "
          f"{rep['bitexact_streams']} non-poisoned streams bit-exact, "
          f"pools drained clean")


def _check_cluster_smoke():
    """The cluster smoke (ISSUE 15): route a shared-prefix trace
    through a 2-replica ClusterFrontDoor — the twin prompts must
    re-land on their prefix owner (affinity hits > 0), the router
    counters must fire, the streams must be bit-identical to a
    cluster-of-1 run of the same trace, and the merged ClusterExporter
    snapshot must render the dashboard's cluster line."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import (
        ClusterFrontDoor, ClusterReplica, ClusterRouter, ServingEngine,
        no_shed_policy,
    )
    from .export import ClusterExporter, render_dashboard

    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    shared = rng.randint(1, cfg.vocab_size, 8).tolist()
    prompts = [shared + rng.randint(1, cfg.vocab_size,
                                    2 + i).tolist()
               for i in range(4)]

    def drive(n_replicas):
        reps = [ClusterReplica(
                    f"r{i}",
                    ServingEngine(model, num_slots=2, block_size=4,
                                  prefix_cache=True),
                    policy=no_shed_policy())
                for i in range(n_replicas)]
        cfd = ClusterFrontDoor(ClusterRouter(reps, affinity_blocks=2))
        streams = [cfd.submit(p, max_new_tokens=2, seed=0)
                   for p in prompts]
        cfd.run_until_idle()
        return cfd, [list(s.result()) for s in streams]

    cfd2, out2 = drive(2)
    cfd1, out1 = drive(1)
    if out2 != out1:
        raise AssertionError(
            f"cluster-of-2 streams diverged from cluster-of-1: "
            f"{out2} vs {out1}")
    st = cfd2.router.affinity_stats()
    if st["keyed_requests"] != len(prompts) or st["affinity_hits"] < 1:
        raise AssertionError(
            f"shared prefixes never re-landed on their owner: {st}")
    reqs = cfd2.router._c_requests
    routed = int(sum(reqs.value(replica=r.name, reason=reason)
                     for r in cfd2.router.replicas
                     for reason in ("affinity", "balance", "failover")))
    if routed != len(prompts):
        raise AssertionError(
            f"router accounted {routed} placements for "
            f"{len(prompts)} requests")
    exp = ClusterExporter.for_cluster(cfd2)
    frame = render_dashboard(exp.registry.snapshot())
    if " cluster " not in frame:
        raise AssertionError("dashboard frame missing cluster line")
    print(f"cluster smoke: routed={routed} "
          f"affinity_hits={st['affinity_hits']} "
          f"hit_rate={st['hit_rate']:.2f}, 2-replica streams "
          f"bit-identical to cluster-of-1, merged dashboard ok")


def _cmd_check(args):
    """Instrumented-fingerprint gate: the serving recipes construct
    their engines with full observability ON (analysis/recipes.py);
    budgets + goldens must hold anyway, and the instrumentation must
    have actually observed the prefill step it rode along with."""
    from paddle_tpu import analysis
    from .trace import validate_chrome_trace

    failed = False
    for name in (args.recipe or _CHECK_RECIPES):
        recipe = analysis.build_recipe(name)
        try:
            report = recipe.check()  # budget (incl. 0 host callbacks)
            analysis.check_recipe_fingerprint(name, report)
            engine = getattr(recipe, "engine", None)
            if engine is None:
                raise AssertionError(
                    f"{name}: recipe carries no engine handle")
            if engine.obs.tracer is None:
                raise AssertionError(
                    f"{name}: engine built without tracing — the gate "
                    f"must audit the INSTRUMENTED engine")
            if engine.stats["steps"] < 1 \
                    or engine.obs.registry.get(
                        "serving_requests_admitted_total").value() < 1:
                raise AssertionError(
                    f"{name}: instrumentation recorded nothing")
            validate_chrome_trace(engine.obs.tracer.chrome_trace())
            print(f"{name}: budget ok, fingerprint ok, "
                  f"{len(engine.obs.tracer.events)} trace events, "
                  f"{report.host_sync.count} host callbacks")
        except (analysis.BudgetViolation, analysis.FingerprintMismatch,
                AssertionError, ValueError) as e:
            failed = True
            print(f"{name}: FAIL — {e}", file=sys.stderr)
        finally:
            recipe.close()
    try:
        _check_slo_smoke()
    except (AssertionError, ValueError) as e:
        failed = True
        print(f"slo smoke: FAIL — {e}", file=sys.stderr)
    try:
        _check_frontdoor_smoke()
    except (AssertionError, ValueError) as e:
        failed = True
        print(f"front-door smoke: FAIL — {e}", file=sys.stderr)
    try:
        _check_prefix_smoke()
    except (AssertionError, ValueError) as e:
        failed = True
        print(f"prefix smoke: FAIL — {e}", file=sys.stderr)
    try:
        _check_int8_smoke()
    except (AssertionError, ValueError) as e:
        failed = True
        print(f"int8 smoke: FAIL — {e}", file=sys.stderr)
    try:
        _check_attribution_smoke()
    except (AssertionError, ValueError, KeyError) as e:
        failed = True
        print(f"attribution smoke: FAIL — {e}", file=sys.stderr)
    try:
        _check_resilience_smoke()
    except (AssertionError, ValueError, RuntimeError) as e:
        failed = True
        print(f"resilience smoke: FAIL — {e}", file=sys.stderr)
    try:
        _check_cluster_smoke()
    except (AssertionError, ValueError) as e:
        failed = True
        print(f"cluster smoke: FAIL — {e}", file=sys.stderr)
    if failed:
        return 1
    print("obs check: instrumentation-enabled fingerprints unchanged")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.obs",
        description="runtime observability CLI (see module docstring)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("snapshot", help="render a metrics snapshot")
    p.add_argument("--in", dest="infile", default=None,
                   help="saved snapshot JSON to re-render")
    p.add_argument("--demo", action="store_true",
                   help="drive a tiny CPU serving engine instead")
    p.add_argument("--spec", action="store_true",
                   help="demo uses the speculative arm")
    p.add_argument("--format", choices=("prom", "json"),
                   default="prom")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=_cmd_snapshot)

    p = sub.add_parser("export",
                       help="write/validate a Chrome trace JSON")
    p.add_argument("--in", dest="infile", default=None,
                   help="existing trace to validate")
    p.add_argument("--demo", action="store_true")
    p.add_argument("--spec", action="store_true")
    p.add_argument("--out", default=None)
    p.add_argument("--metrics-out", default=None,
                   help="also dump the demo registry snapshot here")
    p.set_defaults(fn=_cmd_export)

    p = sub.add_parser("serve",
                       help="live HTTP exporter over the demo engine")
    p.add_argument("--demo", action="store_true", default=True,
                   help="(implied) drive the demo engine")
    p.add_argument("--spec", action="store_true")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9100)
    p.add_argument("--duration", type=float, default=None,
                   help="serve for N seconds then exit "
                        "(default: until Ctrl-C)")
    p.set_defaults(fn=_cmd_serve)

    p = sub.add_parser("slo",
                       help="evaluate the burn-rate health report")
    p.add_argument("--demo", action="store_true")
    p.add_argument("--spec", action="store_true")
    p.add_argument("--in", dest="infile", default=None,
                   help="saved series snapshot (slo --demo --out)")
    p.add_argument("--out", default=None,
                   help="with --demo: also dump the series snapshot")
    p.add_argument("--fail-on", choices=("warn", "critical"),
                   default=None,
                   help="exit 1 when the state reaches this level")
    p.set_defaults(fn=_cmd_slo)

    p = sub.add_parser("watch", help="terminal health dashboard")
    p.add_argument("--url", default=None,
                   help="base URL of a running exporter (serve)")
    p.add_argument("--in", dest="infile", default=None,
                   help="saved registry snapshot JSON")
    p.add_argument("--slo-in", dest="slo_in", default=None,
                   help="saved /slo report JSON (with --in)")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--frames", type=int, default=None,
                   help="stop after N frames (default: loop on --url, "
                        "1 on --in)")
    p.set_defaults(fn=_cmd_watch)

    p = sub.add_parser("check",
                       help="instrumented-fingerprint CI gate "
                            "+ SLO/flight smoke")
    p.add_argument("--recipe", action="append", default=None,
                   choices=_CHECK_RECIPES)
    p.set_defaults(fn=_cmd_check)

    args = ap.parse_args(argv)
    if args.cmd == "check":
        _ensure_check_devices(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
