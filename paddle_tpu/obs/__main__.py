"""Observability CLI::

    python -m paddle_tpu.obs snapshot --in metrics.json [--format prom]
    python -m paddle_tpu.obs snapshot --demo [--format prom|json]
    python -m paddle_tpu.obs export --demo --out trace.json \
        [--metrics-out metrics.json] [--spec]
    python -m paddle_tpu.obs export --in trace.json      # validate
    python -m paddle_tpu.obs check                       # CI gate

``snapshot`` renders a metrics snapshot (live from the ``--demo``
engine run, or re-rendered offline from a saved ``--in`` JSON dump) as
Prometheus text or stable-sorted JSON. ``export`` writes/validates the
Chrome trace-event JSON (open in Perfetto / chrome://tracing); with
``--demo`` it drives a tiny CPU serving engine (``--spec`` switches it
to the speculative arm) so the artifact carries real request spans.
``check`` is the instrumentation-can't-change-the-graph gate used by
``scripts/check_graphs.sh``: it builds the serving + speculative
analysis recipes — whose engines run with FULL observability (registry
+ tracer) — re-checks their budgets, compares the golden fingerprints,
and asserts the instrumentation actually recorded (metrics counted,
trace validates). Exit non-zero on drift.
"""
from __future__ import annotations

import argparse
import json
import sys


def _demo_engine(spec=False, trace=True):
    """A tiny CPU serving run with full instrumentation: a handful of
    ragged requests through prefill/decode (+ the speculative arm),
    enough to populate every serving metric and trace track."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine

    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False)
    model = LlamaForCausalLM(cfg)
    kw = {}
    if spec:
        paddle.seed(7)
        kw = dict(
            spec_draft=LlamaForCausalLM(LlamaConfig.tiny(
                tensor_parallel=False, num_hidden_layers=1)),
            spec_gamma=2)
    engine = ServingEngine(model, num_slots=3, block_size=4,
                           prefill_chunk=4, decode_quantum=3,
                           trace=trace, **kw)
    rng = np.random.RandomState(0)
    for n, mn in ((5, 6), (9, 4), (3, 8), (12, 5)):
        engine.submit(rng.randint(1, cfg.vocab_size, n)
                      .astype(np.int32), max_new_tokens=mn)
    engine.run()
    return engine


def _cmd_snapshot(args):
    from .registry import prometheus_from_snapshot

    if args.demo:
        snap = _demo_engine(spec=args.spec,
                            trace=False).obs.registry.snapshot()
    elif args.infile:
        with open(args.infile) as f:
            snap = json.load(f)
    else:
        print("snapshot: need --demo or --in FILE", file=sys.stderr)
        return 2
    text = (prometheus_from_snapshot(snap) if args.format == "prom"
            else json.dumps(snap, indent=2, sort_keys=True) + "\n")
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0


def _cmd_export(args):
    from .trace import load_chrome_trace

    if args.demo:
        if not args.out:
            print("export --demo: need --out FILE", file=sys.stderr)
            return 2
        engine = _demo_engine(spec=args.spec, trace=True)
        engine.obs.tracer.save(args.out)
        n = len(engine.obs.tracer.events)
        print(f"wrote {args.out}: {n} trace events "
              f"({engine.obs.tracer.dropped} dropped)", file=sys.stderr)
        if args.metrics_out:
            with open(args.metrics_out, "w") as f:
                f.write(engine.obs.registry.snapshot_json(indent=2))
            print(f"wrote {args.metrics_out}", file=sys.stderr)
        return 0
    if args.infile:
        obj = load_chrome_trace(args.infile)
        print(f"{args.infile}: valid chrome trace, "
              f"{len(obj['traceEvents'])} events", file=sys.stderr)
        return 0
    print("export: need --demo or --in FILE", file=sys.stderr)
    return 2


_CHECK_RECIPES = ("serving_decode_step", "speculative_verify_step")


def _cmd_check(args):
    """Instrumented-fingerprint gate: the serving recipes construct
    their engines with full observability ON (analysis/recipes.py);
    budgets + goldens must hold anyway, and the instrumentation must
    have actually observed the prefill step it rode along with."""
    from paddle_tpu import analysis
    from .trace import validate_chrome_trace

    failed = False
    for name in (args.recipe or _CHECK_RECIPES):
        recipe = analysis.build_recipe(name)
        try:
            report = recipe.check()  # budget (incl. 0 host callbacks)
            analysis.check_recipe_fingerprint(name, report)
            engine = getattr(recipe, "engine", None)
            if engine is None:
                raise AssertionError(
                    f"{name}: recipe carries no engine handle")
            if engine.obs.tracer is None:
                raise AssertionError(
                    f"{name}: engine built without tracing — the gate "
                    f"must audit the INSTRUMENTED engine")
            if engine.stats["steps"] < 1 \
                    or engine.obs.registry.get(
                        "serving_requests_admitted_total").value() < 1:
                raise AssertionError(
                    f"{name}: instrumentation recorded nothing")
            validate_chrome_trace(engine.obs.tracer.chrome_trace())
            print(f"{name}: budget ok, fingerprint ok, "
                  f"{len(engine.obs.tracer.events)} trace events, "
                  f"{report.host_sync.count} host callbacks")
        except (analysis.BudgetViolation, analysis.FingerprintMismatch,
                AssertionError, ValueError) as e:
            failed = True
            print(f"{name}: FAIL — {e}", file=sys.stderr)
        finally:
            recipe.close()
    if failed:
        return 1
    print("obs check: instrumentation-enabled fingerprints unchanged")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.obs",
        description="runtime observability CLI (see module docstring)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("snapshot", help="render a metrics snapshot")
    p.add_argument("--in", dest="infile", default=None,
                   help="saved snapshot JSON to re-render")
    p.add_argument("--demo", action="store_true",
                   help="drive a tiny CPU serving engine instead")
    p.add_argument("--spec", action="store_true",
                   help="demo uses the speculative arm")
    p.add_argument("--format", choices=("prom", "json"),
                   default="prom")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=_cmd_snapshot)

    p = sub.add_parser("export",
                       help="write/validate a Chrome trace JSON")
    p.add_argument("--in", dest="infile", default=None,
                   help="existing trace to validate")
    p.add_argument("--demo", action="store_true")
    p.add_argument("--spec", action="store_true")
    p.add_argument("--out", default=None)
    p.add_argument("--metrics-out", default=None,
                   help="also dump the demo registry snapshot here")
    p.set_defaults(fn=_cmd_export)

    p = sub.add_parser("check",
                       help="instrumented-fingerprint CI gate")
    p.add_argument("--recipe", action="append", default=None,
                   choices=_CHECK_RECIPES)
    p.set_defaults(fn=_cmd_check)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
