"""paddle_tpu.obs — runtime observability: metrics registry, request
tracing, and hot-path-safe serving/training telemetry.

The static tier (:mod:`paddle_tpu.analysis`) can prove a compiled
graph's SHAPE (collectives, remat, donation, fingerprints); this
package is the RUNTIME half: what is TTFT / tokens-per-second /
spec-decode acceptance doing over time, per request and per step.

Layout:

- :mod:`.registry` — :class:`MetricsRegistry` with counters, gauges
  and fixed-bucket histograms; Prometheus text exposition and a
  stable-sorted JSON snapshot.
- :mod:`.trace` — :class:`TraceRecorder`: bounded Chrome trace-event
  buffer (``X``/``i``/``C``/``M`` phases), exported as Perfetto-
  loadable JSON; ``validate_chrome_trace`` / ``load_chrome_trace``
  round-trip the schema.
- :mod:`.serving` — :class:`ServingObs`: the engine's boundary hooks
  (ttft/e2e/inter-token histograms, windowed tok/s, acceptance-rate
  series, pool gauges, per-request spans) + the legacy
  ``engine.stats`` compatibility view.
- :mod:`.train` — :class:`InstrumentedTrainStep`: step time, tokens/s
  and MFU (via :mod:`paddle_tpu.profiler.mfu`) into the same registry.
- :mod:`.slo` — declarative :class:`SLO` objectives evaluated with
  SRE-style multi-window burn rates over the serving sample series;
  ordered ``OK < WARN < CRITICAL`` health (:class:`HealthState`).
- :mod:`.export` — :class:`MetricsExporter`: stdlib threaded HTTP
  server exposing live ``/metrics`` (Prometheus text), ``/healthz``
  (SLO state + status code), ``/slo``, ``/snapshot``, ``/anomalies``;
  plus the ``watch`` terminal-dashboard renderer.
- :mod:`.flight` — :class:`FlightRecorder`: bounded per-request
  lifecycle journals with dump-on-anomaly (SLO threshold crossings,
  recompute-waste spikes) to schema-validated JSONL.
- :mod:`.attribution` — :class:`CostLedger`: per-token cost
  attribution over the same boundaries (emitted tokens + dispatch
  walls by phase: prefill / decode / spec_verify /
  preempt_recompute), useful-token-fraction, prefix prefill savings
  and serving-MFU gauges; ``engine.attribution()`` is its report.

The hard invariant, enforced by the golden-fingerprint gate: every
hook runs on the host at a quantum/step boundary — the jitted decode
quantum, speculative round, and train step keep ``max_host_callbacks=
0`` and byte-identical fingerprints with observability enabled.

CLI::

    python -m paddle_tpu.obs snapshot --demo --format prom
    python -m paddle_tpu.obs export --demo --out /tmp/trace.json
    python -m paddle_tpu.obs serve --demo --port 9100   # live exporter
    python -m paddle_tpu.obs slo --demo                 # health report
    python -m paddle_tpu.obs watch --url http://127.0.0.1:9100
    python -m paddle_tpu.obs check   # instrumented fingerprint gate
                                     # + SLO/flight smoke
"""
from .registry import (  # noqa: F401
    Counter, Gauge, Histogram, LATENCY_BUCKETS, MetricsRegistry,
    prometheus_from_snapshot,
)
from .trace import (  # noqa: F401
    TraceRecorder, load_chrome_trace, validate_chrome_trace,
)
from .serving import ServingObs  # noqa: F401
from .train import InstrumentedTrainStep  # noqa: F401
from .slo import (  # noqa: F401
    CRITICAL, OK, WARN, HealthState, SLO, SLOSet,
    default_serving_slos, state_of, worst_state,
)
from .flight import (  # noqa: F401
    FlightRecorder, load_flight_records, validate_flight_records,
)
from .attribution import (  # noqa: F401
    CostLedger, decode_flops_per_token,
)
from .export import (  # noqa: F401
    ClusterExporter, MetricsExporter, render_dashboard,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "LATENCY_BUCKETS",
    "MetricsRegistry", "prometheus_from_snapshot",
    "TraceRecorder", "load_chrome_trace", "validate_chrome_trace",
    "ServingObs", "InstrumentedTrainStep",
    "HealthState", "OK", "WARN", "CRITICAL", "state_of", "worst_state",
    "SLO", "SLOSet", "default_serving_slos",
    "FlightRecorder", "validate_flight_records", "load_flight_records",
    "CostLedger", "decode_flops_per_token",
    "MetricsExporter", "ClusterExporter", "render_dashboard",
]
