"""Metrics registry — the runtime-observability counterpart of the
static analysis tier (reference: the C++ monitor/statistics registry
the serving stack exports, ``paddle/fluid/platform/monitor.h`` and the
2.6-era serving metrics endpoints — unverified, SURVEY.md §0).

Three instrument kinds, all label-aware:

- :class:`Counter` — monotonically increasing float (resettable
  through the legacy stats view's ``_set`` or the explicit
  bench-warmup :meth:`MetricsRegistry.reset`).
- :class:`Gauge` — last-write-wins scalar.
- :class:`Histogram` — FIXED upper-bound buckets declared at creation
  (never rebucketed at runtime: observation cost is one bisect + two
  adds, safe for quantum-boundary hot paths).

Two export surfaces, both deterministic:

- :meth:`MetricsRegistry.snapshot` — a stable-sorted JSON-able dict
  (metrics by name, series by label items) so two snapshots of the
  same state are byte-identical through ``json.dumps``.
- :meth:`MetricsRegistry.prometheus` — Prometheus text exposition
  (``# HELP`` / ``# TYPE``, cumulative ``_bucket{le=...}`` +
  ``_sum``/``_count`` for histograms). ``prometheus_from_snapshot``
  renders the same text from a SAVED snapshot, so the CLI can re-expose
  a dump without the live process.

Everything here is host-side python over plain dicts — no jax imports,
nothing that can leak into a trace.
"""
from __future__ import annotations

import bisect
import json
import math

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "prometheus_from_snapshot", "LATENCY_BUCKETS",
]

# shared default for latency-in-seconds histograms: 100 µs .. 10 s,
# roughly log-spaced (prometheus client_golang's defaults widened one
# decade down — quantum dispatches on small models sit under 1 ms)
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_VALID_TYPES = ("counter", "gauge", "histogram")


def _label_key(labels):
    """Canonical hashable form of a label dict: sorted (k, v) tuples,
    values coerced to str (prometheus labels are strings)."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """Shared name/help/label bookkeeping; one ``_series`` entry per
    distinct label set."""

    type = None  # overridden

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._series = {}  # _label_key -> per-kind state

    def _labels_of(self, key):
        return {k: v for k, v in key}

    def reset(self):
        """Drop every recorded series (the instrument and its buckets
        stay registered). The bench-warmup reset: clears counters,
        gauges AND histogram observations in one call, replacing the
        old hand-zeroing through the legacy stats view."""
        self._series.clear()


class Counter(_Metric):
    type = "counter"

    def inc(self, amount=1.0, **labels):
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (amount={amount}); "
                f"use a Gauge")
        key = _label_key(labels)
        self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels):
        return self._series.get(_label_key(labels), 0.0)

    def _set(self, value, **labels):
        """Reset hook for the legacy ServingEngine.stats view and bench
        warmup resets — intentionally private: counters are monotonic
        to every other caller."""
        self._series[_label_key(labels)] = float(value)


class Gauge(_Metric):
    type = "gauge"

    def set(self, value, **labels):
        self._series[_label_key(labels)] = float(value)

    def inc(self, amount=1.0, **labels):
        key = _label_key(labels)
        self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels):
        return self._series.get(_label_key(labels), 0.0)

    _set = set


class Histogram(_Metric):
    """Fixed-bucket histogram: ``buckets`` are the finite upper bounds
    (strictly increasing); the implicit ``+Inf`` bucket is the overflow.
    Internal counts are PER-BUCKET (non-cumulative); the exposition
    renders the cumulative prometheus form."""

    type = "histogram"

    def __init__(self, name, help="", buckets=LATENCY_BUCKETS):
        super().__init__(name, help)
        bs = [float(b) for b in buckets]
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(
                f"histogram {name}: buckets must be non-empty and "
                f"strictly increasing, got {buckets}")
        if any(math.isinf(b) for b in bs):
            raise ValueError(
                f"histogram {name}: +Inf bucket is implicit")
        self.buckets = tuple(bs)

    def observe(self, value, **labels):
        key = _label_key(labels)
        st = self._series.get(key)
        if st is None:
            st = self._series[key] = {
                "counts": [0] * (len(self.buckets) + 1),
                "sum": 0.0, "count": 0,
            }
        v = float(value)
        # first bucket whose upper bound >= v (prometheus `le` is <=)
        st["counts"][bisect.bisect_left(self.buckets, v)] += 1
        st["sum"] += v
        st["count"] += 1

    def count(self, **labels):
        st = self._series.get(_label_key(labels))
        return st["count"] if st else 0

    def sum(self, **labels):
        st = self._series.get(_label_key(labels))
        return st["sum"] if st else 0.0

    def bucket_counts(self, **labels):
        """Non-cumulative per-bucket counts (len(buckets) + 1 for the
        +Inf overflow)."""
        st = self._series.get(_label_key(labels))
        return (list(st["counts"]) if st
                else [0] * (len(self.buckets) + 1))

    def quantile(self, q, **labels):
        """Bucket-interpolated quantile estimate (the exposition-side
        approximation dashboards use); None when empty."""
        st = self._series.get(_label_key(labels))
        if not st or not st["count"]:
            return None
        target = q * st["count"]
        seen = 0
        lo = 0.0
        for i, c in enumerate(st["counts"]):
            if seen + c >= target and c:
                hi = (self.buckets[i] if i < len(self.buckets)
                      else self.buckets[-1])
                frac = (target - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
            if i < len(self.buckets):
                lo = self.buckets[i]
        return self.buckets[-1]


class MetricsRegistry:
    """Create-or-get instrument factory + the two exporters. Metric
    names are unique across kinds; re-registration with a different
    kind (or different histogram buckets) raises."""

    def __init__(self):
        self._metrics = {}

    def _get(self, cls, name, help, **kw):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.type}")
            if kw.get("buckets") is not None \
                    and tuple(float(b) for b in kw["buckets"]) != m.buckets:
                raise ValueError(
                    f"histogram {name!r} re-registered with different "
                    f"buckets")
            return m
        m = cls(name, help, **{k: v for k, v in kw.items()
                               if v is not None})
        self._metrics[name] = m
        return m

    def counter(self, name, help=""):
        return self._get(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get(Gauge, name, help)

    def histogram(self, name, help="", buckets=None):
        return self._get(Histogram, name, help, buckets=buckets)

    def get(self, name):
        return self._metrics.get(name)

    def names(self):
        return sorted(self._metrics)

    def reset(self):
        """Reset every registered instrument (see
        :meth:`_Metric.reset`): one call returns the registry to its
        just-registered state between bench warmup and timed phases."""
        for m in self._metrics.values():
            m.reset()

    # -- export ------------------------------------------------------------
    def snapshot(self):
        """Stable-sorted JSON-able dict: metrics sorted by name, series
        sorted by label items. json.dumps of two snapshots of identical
        state are byte-identical."""
        metrics = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            entry = {"name": name, "type": m.type, "help": m.help}
            if m.type == "histogram":
                entry["buckets"] = list(m.buckets)
            series = []
            for key in sorted(m._series):
                labels = {k: v for k, v in key}
                if m.type == "histogram":
                    st = m._series[key]
                    series.append({"labels": labels,
                                   "counts": list(st["counts"]),
                                   "sum": st["sum"],
                                   "count": st["count"]})
                else:
                    series.append({"labels": labels,
                                   "value": m._series[key]})
            entry["series"] = series
            metrics.append(entry)
        return {"version": 1, "metrics": metrics}

    def snapshot_json(self, indent=None):
        return json.dumps(self.snapshot(), indent=indent,
                          sort_keys=True)

    def prometheus(self):
        return prometheus_from_snapshot(self.snapshot())


def _fmt_value(v):
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _fmt_labels(labels, extra=()):
    items = list(labels.items()) + list(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in items)
    return "{" + body + "}"


def prometheus_from_snapshot(snap):
    """Prometheus text exposition (v0.0.4) of a :meth:`snapshot` dict —
    shared by the live registry and the CLI's offline re-render."""
    if snap.get("version") != 1:
        raise ValueError(
            f"unsupported snapshot version {snap.get('version')!r}")
    out = []
    for m in snap["metrics"]:
        name, typ = m["name"], m["type"]
        if typ not in _VALID_TYPES:
            raise ValueError(f"metric {name!r}: unknown type {typ!r}")
        if m.get("help"):
            out.append(f"# HELP {name} {m['help']}")
        out.append(f"# TYPE {name} {typ}")
        for s in m["series"]:
            labels = s.get("labels", {})
            if typ == "histogram":
                cum = 0
                for le, c in zip(list(m["buckets"]) + [math.inf],
                                 s["counts"]):
                    cum += c
                    out.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, [('le', _fmt_value(le))])}"
                        f" {cum}")
                out.append(f"{name}_sum{_fmt_labels(labels)} "
                           f"{_fmt_value(s['sum'])}")
                out.append(f"{name}_count{_fmt_labels(labels)} "
                           f"{s['count']}")
            else:
                out.append(f"{name}{_fmt_labels(labels)} "
                           f"{_fmt_value(s['value'])}")
    return "\n".join(out) + "\n"
