"""Train-side telemetry: wrap a :class:`~paddle_tpu.jit.train.
JittedTrainStep` (or any ``step(inputs, labels) -> loss`` callable)
and feed step time / tokens-per-second / MFU into the SAME registry
the serving engine exports — one scrape surface for both halves of the
stack.

The wrapper times the dispatch ON THE HOST, after the jitted program
returns: the compiled train step itself is untouched (same
``llama_tp_zero_fused_lce`` fingerprint), and the only behavioral knob
is ``sync`` — blocking on (a leaf of) the loss each step for honest
wall-clock, exactly what a train loop that logs its loss already pays.
Set ``sync=False`` to time dispatch only (pipelined loops that block
elsewhere).

MFU accounting reuses :mod:`paddle_tpu.profiler.mfu` — model FLOPs per
step over the chip's peak; on backends without a known peak (the CPU
tier-1 backend) the MFU gauge is simply not set and throughput gauges
still export.
"""
from __future__ import annotations

import time
from collections import deque

from .registry import LATENCY_BUCKETS, MetricsRegistry

__all__ = ["InstrumentedTrainStep"]

# step-time buckets: LATENCY_BUCKETS plus a slow tail for big-model
# steps (10 s .. 120 s)
_STEP_BUCKETS = tuple(LATENCY_BUCKETS) + (30.0, 60.0, 120.0)


class InstrumentedTrainStep:
    """Telemetry proxy around a train step.

    Args:
        step: the wrapped step — typically a
            :class:`~paddle_tpu.jit.train.JittedTrainStep`; every
            attribute this proxy does not define (``lower``,
            ``step_jaxpr``, ``donatable_leaf_count``, ``run_steps``,
            ``sync_to_model``, ``params``, ...) passes straight
            through, so the analysis hooks audit the SAME object.
        registry: target :class:`MetricsRegistry` (default: fresh).
        name: metric name prefix (``<name>_step_seconds``, ...).
        tokens_per_step: tokens consumed per step — enables the
            ``_tokens_total`` counter and tokens/s gauges.
        model_flops_per_step: model FLOPs per step (see
            :func:`paddle_tpu.profiler.mfu.transformer_train_flops`) —
            enables the MFU / TFLOP/s gauges when the chip peak is
            known.
        n_chips: chips the step spans (peak = per-chip peak × n).
        sync: block on the loss before stopping the clock.
        tracer: optional :class:`~paddle_tpu.obs.trace.TraceRecorder`
            — one ``X`` span per step on the train track.
    """

    def __init__(self, step, registry=None, name="train",
                 tokens_per_step=None, model_flops_per_step=None,
                 n_chips=1, sync=True, tracer=None):
        self._step = step
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.name = str(name)
        self.tokens_per_step = (None if tokens_per_step is None
                                else int(tokens_per_step))
        self.model_flops_per_step = (
            None if model_flops_per_step is None
            else float(model_flops_per_step))
        self._sync = bool(sync)
        self.tracer = tracer
        from ..profiler.mfu import peak_flops_per_chip

        self.peak_flops = peak_flops_per_chip() * int(n_chips)
        r = self.registry
        self._h_step = r.histogram(
            f"{self.name}_step_seconds", "one train step, host wall",
            buckets=_STEP_BUCKETS)
        self._c_steps = r.counter(
            f"{self.name}_steps_total", "train steps dispatched")
        self._c_tokens = r.counter(
            f"{self.name}_tokens_total", "tokens consumed")
        self._g_tok_s = r.gauge(
            f"{self.name}_tokens_per_second", "last-step tokens/s")
        self._g_mfu = r.gauge(
            f"{self.name}_mfu", "model-FLOP utilization (0..1)")
        self._g_tflops = r.gauge(
            f"{self.name}_model_tflops_per_second",
            "achieved model TFLOP/s")
        self._times = deque(maxlen=4096)

    @classmethod
    def for_transformer(cls, step, *, n_params, tokens_per_step,
                        num_layers=0, seq_len=0, hidden=0, causal=True,
                        **kw):
        """Convenience: derive ``model_flops_per_step`` from the
        standard 6NT(+attention) accounting in profiler.mfu."""
        from ..profiler.mfu import transformer_train_flops

        flops = transformer_train_flops(
            n_params, tokens_per_step, num_layers=num_layers,
            seq_len=seq_len, hidden=hidden, causal=causal)
        return cls(step, tokens_per_step=tokens_per_step,
                   model_flops_per_step=flops, **kw)

    def __call__(self, inputs, labels):
        t0 = time.perf_counter()
        loss = self._step(inputs, labels)
        if self._sync:
            from ..profiler.mfu import _block

            _block(loss, None)
        t1 = time.perf_counter()
        dt = t1 - t0
        self._times.append(dt)
        self._h_step.observe(dt)
        self._c_steps.inc()
        if self.tokens_per_step:
            self._c_tokens.inc(self.tokens_per_step)
            self._g_tok_s.set(self.tokens_per_step / dt)
        if self.model_flops_per_step:
            achieved = self.model_flops_per_step / dt
            self._g_tflops.set(achieved / 1e12)
            if self.peak_flops:
                self._g_mfu.set(achieved / self.peak_flops)
        if self.tracer is not None:
            self.tracer.thread_name(100, self.name)
            self.tracer.complete(f"{self.name}_step", t0, t1, tid=100)
        return loss

    def report(self):
        """MFUMeter-shaped summary over the recorded steps (median step
        time; empty dict before the first step)."""
        if not self._times:
            return {}
        ts = sorted(self._times)
        step_time = ts[len(ts) // 2]
        out = {"step_time_s": step_time, "n_steps_timed": len(ts)}
        if self.tokens_per_step:
            out["tokens_per_sec"] = self.tokens_per_step / step_time
        if self.model_flops_per_step:
            achieved = self.model_flops_per_step / step_time
            out["model_tflops_per_sec"] = achieved / 1e12
            out["mfu"] = (achieved / self.peak_flops
                          if self.peak_flops else None)
        return out

    def __getattr__(self, attr):
        # analysis hooks (lower/step_jaxpr/donatable_leaf_count/...)
        # and state accessors hit the wrapped step unchanged
        return getattr(self._step, attr)
