"""Per-token cost ledger: attribute every quantum's wall time and
every emitted token to a PHASE, at the host boundaries PR 5
established — the attribution layer never enters a compiled program
(the ``serving_decode_step``/``speculative_verify_step`` goldens stay
byte-identical with the ledger fully on; ``max_host_callbacks=0``
still holds).

Phases:

- ``prefill`` — tokens emitted at prefill completion, and the novel
  (first-computed) share of mixed-step wall time.
- ``decode`` — tokens from decode rows (mixed steps) and jitted decode
  quanta, plus their wall time.
- ``spec_verify`` — tokens emitted by speculative rounds (draft-γ +
  verify in one dispatch) and the rounds' wall time.
- ``preempt_recompute`` — wall time the engine spent RE-prefilling
  tokens a preemption dropped (recompute-on-resume debt). The matching
  token count is waste, not emission, so it lives in the prefill WORK
  split below, never in the emitted-token phases.

Token conservation is the design invariant (``obs check`` asserts it,
tests/test_attribution.py pins it across a ragged
preempt/resume + spec + prefix-hit run):

- emitted: ``sum_phase serving_attr_tokens_total ==
  serving_tokens_emitted_total`` token-for-token (every ``_emit`` is
  attributed exactly once).
- prefill work: ``novel + recompute == serving_prefill_tokens_total``
  (every enc token the mixed step pushed is classified novel-vs-
  recompute by whether its row ever lost a slot), and ``cached``
  counts prompt tokens the prefix cache SKIPPED (the savings).
- spec waste: ``serving_attr_spec_rejected_tokens_total ==
  serving_spec_proposed_total - serving_spec_accepted_total``.
- time: the per-phase seconds PARTITION the measured quantum walls —
  mixed-step wall is pro-rated across its rows by tokens processed
  (host-side pro-rata; the graph cannot be timed from inside), decode
  and spec walls attribute whole. ``sum_phase seconds == sum of
  serving_quantum_seconds`` within float tolerance.

Derived gauges (refreshed at the same boundaries):

- ``serving_useful_token_fraction`` = emitted / (emitted + recomputed
  + rejected-draft) — the engine's useful-work yield.
- ``serving_prefix_prefill_saved_fraction`` = cached / (cached +
  computed prefill) — what the content-addressed cache is worth.
- ``serving_model_flops_per_second`` = windowed tok/s x model
  FLOPs/token (configured from the model config: the standard 2N
  weight-matmul decode floor — attention FLOPs vary with live context
  and are deliberately excluded rather than guessed), and
  ``serving_mfu_fraction`` = that over the chip's peak
  (:mod:`paddle_tpu.profiler.mfu`; peak is 0.0 off TPU, so the MFU
  gauge honestly reads 0 on the CPU smoke and the raw FLOP/s gauge is
  the portable number).

Nothing here imports jax; the engine configures FLOPs/peak at build
time and ``engine.attribution()`` returns :meth:`CostLedger.report`.
"""
from __future__ import annotations

__all__ = ["CostLedger", "EMIT_PHASES", "TIME_PHASES",
           "decode_flops_per_token"]

#: phases emitted tokens attribute to (sum == tokens_emitted_total)
EMIT_PHASES = ("prefill", "decode", "spec_verify")
#: phases wall time attributes to (sum == quantum walls)
TIME_PHASES = ("prefill", "decode", "spec_verify", "preempt_recompute")


def decode_flops_per_token(n_params, n_embedding_params=0):
    """Model FLOPs per decoded token: the standard ``2N`` weight-
    matmul approximation over the params actually multiplied per token
    (embedding lookups are gathers, not matmuls — pass their count to
    exclude them; the tied lm_head matmul IS counted by keeping it in
    ``n_params``). Attention-over-context FLOPs are excluded, not
    estimated: they depend on each slot's live length, and an honest
    floor beats a guessed mean. See PAPER.md's MFU framing."""
    return 2.0 * float(max(int(n_params) - int(n_embedding_params), 0))


class CostLedger:
    """The attribution instrument set over one registry. Construction
    registers every counter/gauge (stable ``/metrics`` shape); the
    update hooks are driven by :class:`~paddle_tpu.obs.serving.
    ServingObs` at the existing host boundaries and are disabled with
    it (the ``obs="off"`` bench arm)."""

    def __init__(self, registry):
        r = registry
        self.registry = r
        self._c_tokens = r.counter(
            "serving_attr_tokens_total",
            "emitted tokens by phase (prefill|decode|spec_verify); "
            "sums to serving_tokens_emitted_total")
        self._c_seconds = r.counter(
            "serving_attr_seconds_total",
            "dispatch wall seconds by phase (mixed steps pro-rated "
            "across rows by tokens processed)")
        self._c_prefill_work = r.counter(
            "serving_attr_prefill_work_tokens_total",
            "prefill-side token accounting: kind=novel (first "
            "compute), recompute (re-prefill after preemption), "
            "cached (skipped via prefix-cache alias)")
        self._c_spec_rejected = r.counter(
            "serving_attr_spec_rejected_tokens_total",
            "draft tokens proposed but rejected by verification")
        self._g_useful = r.gauge(
            "serving_useful_token_fraction",
            "emitted / (emitted + recomputed + rejected drafts)")
        self._g_saved = r.gauge(
            "serving_prefix_prefill_saved_fraction",
            "prefix-cache-skipped / (skipped + computed) prompt "
            "tokens")
        self._g_flops = r.gauge(
            "serving_model_flops_per_second",
            "windowed tok/s x configured model FLOPs/token")
        self._g_mfu = r.gauge(
            "serving_mfu_fraction",
            "model FLOP/s over peak_flops_per_chip (0 when the chip "
            "is unknown, e.g. the CPU smoke)")
        self.flops_per_token = 0.0
        self.peak_flops = 0.0

    def configure(self, flops_per_token=0.0, peak_flops=0.0):
        """Engine-supplied model/chip constants for the MFU gauges
        (0.0 = unknown; the token/time ledger works regardless)."""
        self.flops_per_token = float(flops_per_token)
        self.peak_flops = float(peak_flops)
        return self

    # -- boundary hooks (driven by ServingObs) -------------------------
    def on_quantum(self, kind, t0, t1, tokens, breakdown=None,
                   window_rate=0.0):
        """Attribute one dispatch. ``decode``/``spec_round`` walls and
        tokens attribute whole; a ``mixed`` step carries ``breakdown``
        = ``{prefill_emitted, decode_emitted, novel_tokens,
        recompute_tokens, decode_rows}`` and its wall is pro-rated by
        tokens processed."""
        wall = max(float(t1) - float(t0), 0.0)
        if kind == "decode":
            if tokens:
                self._c_tokens.inc(int(tokens), phase="decode")
            self._c_seconds.inc(wall, phase="decode")
        elif kind == "spec_round":
            if tokens:
                self._c_tokens.inc(int(tokens), phase="spec_verify")
            self._c_seconds.inc(wall, phase="spec_verify")
        elif kind == "mixed":
            b = breakdown or {}
            pe = int(b.get("prefill_emitted", 0))
            de = int(b.get("decode_emitted", 0))
            novel = int(b.get("novel_tokens", 0))
            recomp = int(b.get("recompute_tokens", 0))
            dec_rows = int(b.get("decode_rows", 0))
            if pe:
                self._c_tokens.inc(pe, phase="prefill")
            if de:
                self._c_tokens.inc(de, phase="decode")
            if novel:
                self._c_prefill_work.inc(novel, kind="novel")
            if recomp:
                self._c_prefill_work.inc(recomp, kind="recompute")
            # pro-rata: each processed token (enc tokens per prefill
            # row, one per decode row) carries an equal slice of the
            # dispatch wall — exact partition, so phase seconds still
            # sum to the measured walls
            total = novel + recomp + dec_rows
            if total:
                share = wall / total
                if novel:
                    self._c_seconds.inc(novel * share, phase="prefill")
                if recomp:
                    self._c_seconds.inc(recomp * share,
                                        phase="preempt_recompute")
                if dec_rows:
                    self._c_seconds.inc(dec_rows * share,
                                        phase="decode")
            else:
                self._c_seconds.inc(wall, phase="prefill")
        else:  # an unknown dispatch kind still lands somewhere
            self._c_seconds.inc(wall, phase=kind)
            if tokens:
                self._c_tokens.inc(int(tokens), phase=kind)
        self._refresh_gauges(window_rate)

    def on_spec_round(self, proposed, accepted):
        rejected = int(proposed) - int(accepted)
        if rejected > 0:
            self._c_spec_rejected.inc(rejected)

    def on_cached_prefill(self, tokens):
        """Prompt tokens an admission SKIPPED via a prefix-cache alias
        (the savings side of the prefill ledger)."""
        if tokens:
            self._c_prefill_work.inc(int(tokens), kind="cached")

    # -- derived views -------------------------------------------------
    def emitted_tokens(self):
        return {p: self._c_tokens.value(phase=p) for p in EMIT_PHASES}

    def phase_seconds(self):
        return {p: self._c_seconds.value(phase=p) for p in TIME_PHASES}

    def prefill_work(self):
        return {k: self._c_prefill_work.value(kind=k)
                for k in ("novel", "recompute", "cached")}

    def waste_tokens(self):
        return {
            "preempt_recompute":
                self._c_prefill_work.value(kind="recompute"),
            "spec_rejected": self._c_spec_rejected.value(),
        }

    def total_attributed_tokens(self):
        """emitted + recomputed + rejected-draft — the conservation
        total the acceptance test checks against the raw counters."""
        return (sum(self.emitted_tokens().values())
                + sum(self.waste_tokens().values()))

    def _refresh_gauges(self, window_rate=0.0):
        emitted = sum(self.emitted_tokens().values())
        waste = sum(self.waste_tokens().values())
        self._g_useful.set(
            emitted / (emitted + waste) if emitted + waste else 1.0)
        w = self.prefill_work()
        computed = w["novel"] + w["recompute"]
        self._g_saved.set(
            w["cached"] / (w["cached"] + computed)
            if w["cached"] + computed else 0.0)
        flops = float(window_rate) * self.flops_per_token
        self._g_flops.set(flops)
        self._g_mfu.set(flops / self.peak_flops if self.peak_flops
                        else 0.0)

    def report(self):
        """The ``engine.attribution()`` payload: the full ledger as
        one JSON-able dict (phases, work split, waste, gauges, MFU
        context)."""
        emitted = self.emitted_tokens()
        seconds = self.phase_seconds()
        waste = self.waste_tokens()
        work = self.prefill_work()
        total_emitted = sum(emitted.values())
        total_seconds = sum(seconds.values())
        return {
            "version": 1,
            "emitted_tokens": {p: int(emitted[p]) for p in emitted},
            "emitted_total": int(total_emitted),
            "phase_seconds": {p: seconds[p] for p in seconds},
            "attributed_seconds": total_seconds,
            "prefill_work_tokens": {k: int(work[k]) for k in work},
            "waste_tokens": {k: int(waste[k]) for k in waste},
            "attributed_tokens_total":
                int(self.total_attributed_tokens()),
            "useful_token_fraction": self._g_useful.value(),
            "prefix_prefill_saved_fraction": self._g_saved.value(),
            "mfu": {
                "flops_per_token": self.flops_per_token,
                "peak_flops_per_chip": self.peak_flops,
                "model_flops_per_second": self._g_flops.value(),
                "mfu_fraction": self._g_mfu.value(),
            },
        }
