"""Live observability export: a stdlib-only threaded HTTP exporter
serving the registry and the SLO layer while the engine runs, plus the
``watch``-style terminal dashboard renderer (reference: the 2.6-era
serving images' metrics/health ports — unverified, SURVEY.md §0).

Endpoints (GET):

- ``/metrics`` — live Prometheus text exposition
  (``registry.prometheus()``), scrapeable by a stock Prometheus.
- ``/healthz`` — the ordered SLO health state as JSON with the HTTP
  status code a load balancer keys on: ``ok``/``warn`` -> 200 (degraded
  still serves), ``critical`` -> 503 (pull it from rotation). With no
  SLOs attached the state is vacuously ``ok``.
- ``/slo`` — the full multi-window burn-rate report
  (:meth:`SLOSet.evaluate`).
- ``/snapshot`` — the registry's stable-sorted JSON snapshot (what the
  ``watch`` dashboard polls).
- ``/anomalies`` — the flight recorder's captured journals as JSONL
  (404 when no recorder is attached).

The server is a ``ThreadingHTTPServer`` on a daemon thread:
``start()``/``stop()`` bound its life, ``port=0`` binds an ephemeral
port (tests scrape ``exporter.port``), and zero third-party deps. The
scrape path only READS host-side dicts/deques the engine thread
mutates at step boundaries; renders retry a few times on the rare
mutated-during-iteration race instead of locking the engine's hot
path.

Nothing here imports jax — the exporter can wrap any registry, engine
or not.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["MetricsExporter", "ClusterExporter", "render_dashboard"]

_STATUS_BY_STATE = {"ok": 200, "warn": 200, "critical": 503}


class MetricsExporter:
    """Serve one registry (+ optional SLO set / obs series / flight
    recorder) over HTTP.

    Args:
        registry: the :class:`MetricsRegistry` behind ``/metrics`` and
            ``/snapshot``.
        slos: :class:`~paddle_tpu.obs.slo.SLOSet` evaluated per
            ``/healthz`` / ``/slo`` request (None -> vacuous ``ok``).
        obs: the :class:`ServingObs` whose sample series the SLOs
            evaluate over (anything with ``timeseries()``).
        flight: :class:`~paddle_tpu.obs.flight.FlightRecorder` behind
            ``/anomalies``.
        host / port: bind address; ``port=0`` picks an ephemeral port
            (read it back from ``self.port`` after ``start()``).
    """

    def __init__(self, registry, slos=None, obs=None, flight=None,
                 host="127.0.0.1", port=0):
        self.registry = registry
        self.slos = slos
        self.obs = obs
        self.flight = flight
        self.host = str(host)
        self.port = int(port)
        self._server = None
        self._thread = None
        # broken renders must not kill the endpoint OR pass silently:
        # every handler exception turns into a JSON 500 and a bump here
        self._c_errors = registry.counter(
            "exporter_errors_total",
            "handler exceptions turned into HTTP 500 responses")

    @classmethod
    def for_engine(cls, engine, host="127.0.0.1", port=0):
        """Wire every surface a :class:`ServingEngine` carries."""
        return cls(engine.obs.registry, slos=engine.slo,
                   obs=engine.obs, flight=engine.flight,
                   host=host, port=port)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._server is not None:
            raise RuntimeError("exporter already started")
        self._server = ThreadingHTTPServer((self.host, self.port),
                                           _make_handler(self))
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="paddle-tpu-obs-exporter", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        self._server = self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def url(self, path="/"):
        return f"http://{self.host}:{self.port}{path}"

    # -- renders (shared by the HTTP handler and direct callers) ----------
    def _retry(self, fn, attempts=3):
        for i in range(attempts):
            try:
                return fn()
            except RuntimeError:  # dict/deque mutated during iteration
                if i == attempts - 1:
                    raise

    def health_report(self, now=None):
        if self.slos is None:
            return {"version": 1, "state": "ok", "now": now,
                    "objectives": []}
        source = self.obs if self.obs is not None else {}
        return self._retry(lambda: self.slos.evaluate(source, now=now))

    def healthz(self, now=None):
        """(HTTP status, body dict) — the state plus one line per
        objective, cheap enough for aggressive LB polling."""
        report = self.health_report(now)
        body = {
            "state": report["state"],
            "objectives": {o["name"]: o["state"]
                           for o in report["objectives"]},
        }
        return _STATUS_BY_STATE[report["state"]], body

    def routes(self):
        return ("/metrics", "/healthz", "/slo", "/snapshot",
                "/anomalies")


def _make_handler(exporter):
    class _Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # scrapes must not spam stderr
            pass

        def _send(self, status, body, ctype):
            data = body.encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            try:
                if path == "/metrics":
                    self._send(
                        200,
                        exporter._retry(exporter.registry.prometheus),
                        "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    status, body = exporter.healthz()
                    self._send(status,
                               json.dumps(body, sort_keys=True) + "\n",
                               "application/json")
                elif path == "/slo":
                    self._send(
                        200,
                        json.dumps(exporter.health_report(),
                                   sort_keys=True) + "\n",
                        "application/json")
                elif path == "/snapshot":
                    self._send(
                        200,
                        exporter._retry(
                            lambda: exporter.registry.snapshot_json())
                        + "\n",
                        "application/json")
                elif path == "/anomalies":
                    if exporter.flight is None:
                        self._send(404, "no flight recorder attached\n",
                                   "text/plain")
                    else:
                        self._send(
                            200,
                            exporter._retry(exporter.flight.jsonl),
                            "application/x-ndjson")
                else:
                    self._send(
                        404,
                        "not found; routes: "
                        + " ".join(exporter.routes()) + "\n",
                        "text/plain")
            except Exception as e:  # a broken render must not kill the
                exporter._c_errors.inc()  # server thread
                try:
                    self._send(
                        500,
                        json.dumps({"error": f"{type(e).__name__}: {e}"},
                                   sort_keys=True) + "\n",
                        "application/json")
                except Exception:
                    pass  # client hung up mid-error; nothing to do

    return _Handler


# ----------------------------------------------- cluster aggregation
class _MergedRegistry:
    """Read-only multi-registry view for :class:`ClusterExporter`: a
    merged snapshot with every member's series relabeled by replica,
    rendered through the same :func:`prometheus_from_snapshot` the
    live registry uses. Exporter-internal instruments (the error
    counter) land in ``own``, which merges UNLABELED — so a fleet
    scrape is exactly the union of the per-replica scrapes plus the
    router/exporter series."""

    def __init__(self, members, own):
        self._members = list(members)   # [(replica_name, registry)]
        self._own = own                 # a real MetricsRegistry

    def counter(self, *a, **kw):
        return self._own.counter(*a, **kw)

    def gauge(self, *a, **kw):
        return self._own.gauge(*a, **kw)

    def snapshot(self):
        merged = {}
        for label, reg in [(None, self._own)] + self._members:
            for m in reg.snapshot()["metrics"]:
                e = merged.get(m["name"])
                if e is None:
                    e = {k: v for k, v in m.items() if k != "series"}
                    e["series"] = []
                    merged[m["name"]] = e
                elif e["type"] != m["type"]:
                    raise ValueError(
                        f"metric {m['name']!r} registered as "
                        f"{e['type']} and {m['type']} across replicas")
                for s in m["series"]:
                    s = dict(s)
                    labels = dict(s.get("labels", {}))
                    if label is not None:
                        labels["replica"] = label
                    s["labels"] = labels
                    e["series"].append(s)
        metrics = []
        for name in sorted(merged):
            e = merged[name]
            e["series"].sort(key=lambda s: sorted(s["labels"].items()))
            metrics.append(e)
        return {"version": 1, "metrics": metrics}

    def snapshot_json(self, indent=None):
        return json.dumps(self.snapshot(), indent=indent,
                          sort_keys=True)

    def prometheus(self):
        from .registry import prometheus_from_snapshot
        return prometheus_from_snapshot(self.snapshot())


class ClusterExporter(MetricsExporter):
    """One scrape for the whole fleet: ``/metrics`` serves every
    replica's registry merged under a ``replica`` label (router and
    exporter series unlabeled), and ``/healthz`` is fleet-level with
    WORST-STATE-WINS — one CRITICAL replica 503s the cluster scrape a
    load balancer keys on, while the per-replica exporters (if any)
    keep answering for themselves.

    Args:
        members: list of ``(replica_name, engine_or_exporter)`` — an
            engine is wrapped in a (non-started) per-replica
            :class:`MetricsExporter` via :meth:`for_engine` for its
            healthz; a ready exporter is used as-is.
        registry: extra UNLABELED registry merged into the scrape
            (pass the cluster router's so ``serving_router_*`` ride
            along); also hosts the exporter's own error counter.
    """

    def __init__(self, members, registry=None, host="127.0.0.1",
                 port=0):
        if registry is None:
            from .registry import MetricsRegistry
            registry = MetricsRegistry()
        self._members = []
        for name, m in members:
            exp = (m if isinstance(m, MetricsExporter)
                   else MetricsExporter.for_engine(m))
            self._members.append((str(name), exp))
        merged = _MergedRegistry(
            [(n, e.registry) for n, e in self._members], registry)
        super().__init__(merged, slos=None, obs=None, flight=None,
                         host=host, port=port)

    @classmethod
    def for_cluster(cls, cluster, host="127.0.0.1", port=0):
        """Wire a :class:`~paddle_tpu.serving.cluster.ClusterFrontDoor`
        (or its router): one member per replica + the router registry."""
        router = getattr(cluster, "router", cluster)
        return cls([(r.name, r.engine) for r in router.replicas],
                   registry=router.registry, host=host, port=port)

    def health_report(self, now=None):
        """Worst-state-wins fleet report with every replica's own
        report nested — the drill-down a fleet 503 points at."""
        per = {n: e.health_report(now) for n, e in self._members}
        worst = max((r["state"] for r in per.values()),
                    key=lambda s: ("ok", "warn", "critical").index(s),
                    default="ok")
        return {"version": 1, "state": worst, "now": now,
                "objectives": [], "replicas": per}

    def healthz(self, now=None):
        report = self.health_report(now)
        body = {
            "state": report["state"],
            "replicas": {n: r["state"]
                         for n, r in report["replicas"].items()},
        }
        return _STATUS_BY_STATE[report["state"]], body


# -------------------------------------------------------- dashboard
def _snap_metric(snap, name):
    for m in snap.get("metrics", ()):
        if m["name"] == name:
            return m
    return None


def _snap_value(snap, name, default=0.0, **labels):
    m = _snap_metric(snap, name)
    if m is None:
        return default
    want = {str(k): str(v) for k, v in labels.items()}
    for s in m["series"]:
        if {str(k): str(v) for k, v in s.get("labels", {}).items()} \
                == want:
            return s.get("value", default)
    return default


def _snap_sum(snap, name):
    """Sum of a metric's series across ALL label sets (e.g. the total
    of a ``{site,kind}``-labeled counter)."""
    m = _snap_metric(snap, name)
    if m is None:
        return 0.0
    return sum(s.get("value", 0.0) for s in m["series"])


def _snap_labels_where(snap, name, pred):
    """Label dicts of a metric's series whose value satisfies
    ``pred`` — e.g. the active modes of the degraded-mode gauge."""
    m = _snap_metric(snap, name)
    if m is None:
        return []
    return [s.get("labels", {}) for s in m["series"]
            if pred(s.get("value", 0.0))]


def _snap_quantile(snap, name, q):
    """Bucket-interpolated quantile from a SNAPSHOT histogram entry
    (label-less series) — the offline twin of ``Histogram.quantile``."""
    m = _snap_metric(snap, name)
    if m is None or m.get("type") != "histogram":
        return None
    for s in m["series"]:
        if s.get("labels"):
            continue
        count = s["count"]
        if not count:
            return None
        buckets = list(m["buckets"])
        target = q * count
        seen, lo = 0, 0.0
        for i, c in enumerate(s["counts"]):
            if seen + c >= target and c:
                hi = buckets[i] if i < len(buckets) else buckets[-1]
                frac = (target - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
            if i < len(buckets):
                lo = buckets[i]
        return buckets[-1]
    return None


def _fmt_s(v):
    if v is None:
        return "   n/a"
    return f"{v * 1e3:6.1f}ms" if v < 1.0 else f"{v:6.2f}s "


def render_dashboard(snapshot, report=None, width=62):
    """One ``watch``-style terminal frame from a registry snapshot and
    an optional SLO report — pure text in, text out, so the CLI can
    render live scrapes and tests can pin the layout."""
    g = lambda name, **lb: _snap_value(snapshot, name, **lb)  # noqa: E731
    bar = "=" * width
    lines = [bar, "paddle_tpu serving health".center(width), bar]
    state = (report or {}).get("state", "n/a")
    marker = {"ok": "[OK]", "warn": "[WARN]",
              "critical": "[CRIT]"}.get(state, "[?]")
    lines.append(f" health: {marker} {state}")
    for o in (report or {}).get("objectives", ()):
        fast = o["windows"]["fast"]
        slow = o["windows"]["slow"]
        lines.append(
            f"   {o['state']:>8}  {o['name']:<16} "
            f"burn fast {fast['burn_rate']:7.2f} (n={fast['n']})  "
            f"slow {slow['burn_rate']:7.2f} (n={slow['n']})")
    lines.append(bar)
    lines.append(
        f" requests  submitted {g('serving_requests_submitted_total'):>7.0f}"
        f"  admitted {g('serving_requests_admitted_total'):>7.0f}"
        f"  finished {g('serving_requests_finished_total'):>7.0f}")
    lines.append(
        f" tokens    emitted   {g('serving_tokens_emitted_total'):>7.0f}"
        f"  rate "
        f"{g('serving_tokens_per_second_window'):>10.1f} tok/s")
    lines.append(
        f" overload  shed {g('serving_requests_shed_total'):>6.0f}"
        f"  preempted {g('serving_requests_preempted_total'):>5.0f}"
        f"  resumed {g('serving_requests_resumed_total'):>5.0f}"
        f"  drains {g('serving_drains_total'):>3.0f}")
    recomputed = g("serving_tokens_recomputed_total")
    if recomputed:
        lines.append(
            f" recompute {recomputed:>6.0f} cached tokens dropped by "
            f"preemption (re-prefilled on resume)")
    # cost-ledger lines (obs/attribution.py) — only once the ledger
    # has attributed something, so pre-ledger snapshots render as
    # before
    attr_emitted = sum(
        g("serving_attr_tokens_total", phase=p)
        for p in ("prefill", "decode", "spec_verify"))
    if attr_emitted:
        lines.append(
            f" attrib    useful "
            f"{g('serving_useful_token_fraction'):6.1%}"
            f"  recomputed "
            f"{g('serving_attr_prefill_work_tokens_total', kind='recompute'):>5.0f}"
            f"  rejected "
            f"{g('serving_attr_spec_rejected_tokens_total'):>5.0f}"
            f"  saved "
            f"{g('serving_prefix_prefill_saved_fraction'):6.1%}")
        flops = g("serving_model_flops_per_second")
        mfu = g("serving_mfu_fraction")
        if flops:
            mfu_txt = (f"{mfu:6.2%}" if mfu
                       else "   n/a (chip peak unknown)")
            lines.append(
                f" mfu       {mfu_txt}  model "
                f"{flops / 1e9:10.3f} GFLOP/s")
    lines.append(
        f" latency   ttft p50 {_fmt_s(_snap_quantile(snapshot, 'serving_ttft_seconds', 0.5))}"
        f"  p95 {_fmt_s(_snap_quantile(snapshot, 'serving_ttft_seconds', 0.95))}"
        f"   e2e p95 {_fmt_s(_snap_quantile(snapshot, 'serving_e2e_latency_seconds', 0.95))}")
    lines.append(f" slots     occupied  {g('serving_slots_occupied'):>7.0f}")
    for pool in ("target", "draft"):
        in_use = g("serving_pool_blocks_in_use", pool=pool)
        free = g("serving_pool_free_blocks", pool=pool)
        if in_use or free:
            util = g("serving_pool_utilization", pool=pool)
            lines.append(
                f" pool[{pool:<6}] blocks {in_use:>6.0f} in use, "
                f"{free:>6.0f} free, util {util:6.1%}")
        for kvd in ("float32", "bfloat16", "float16", "int8"):
            b = g("serving_pool_bytes", pool=pool, kv_dtype=kvd)
            if b:
                chip = g("serving_pool_per_chip_bytes", pool=pool,
                         kv_dtype=kvd)
                per_chip = (f", {chip / 1024.0:8.1f} KiB/chip"
                            if chip and chip != b else "")
                lines.append(
                    f" bytes[{pool:<5}] {b / 1024.0:>8.1f} KiB resident "
                    f"(kv {kvd}{per_chip})")
        hits = g("serving_prefix_cache_hits_total", pool=pool)
        misses = g("serving_prefix_cache_misses_total", pool=pool)
        if hits or misses:
            cow = g("serving_prefix_cache_cow_copies_total", pool=pool)
            frac = g("serving_prefix_cache_cached_block_fraction",
                     pool=pool)
            lines.append(
                f" prefix[{pool:<4}] hits {hits:>6.0f}  misses "
                f"{misses:>6.0f}  cow {cow:>4.0f}  cached {frac:6.1%}")
    # resilience line — only once a fault/retry/trip/quarantine has
    # happened, so pre-resilience snapshots render as before
    faults = _snap_sum(snapshot, "serving_faults_injected_total")
    retries = _snap_sum(snapshot, "serving_quantum_retries_total")
    trips = _snap_sum(snapshot, "serving_watchdog_trips_total")
    quar = _snap_sum(snapshot, "serving_quarantines_total")
    restores = _snap_sum(snapshot, "serving_restores_total")
    if faults or retries or trips or quar or restores:
        lines.append(
            f" faults    injected {faults:>5.0f}  retries {retries:>4.0f}"
            f"  watchdog {trips:>4.0f}  quarantined {quar:>4.0f}"
            f"  restores {restores:>3.0f}")
    modes = sorted(lb.get("mode", "?") for lb in _snap_labels_where(
        snapshot, "serving_degraded_mode", lambda v: v >= 1.0))
    if modes:
        lines.append(f" degraded  {', '.join(modes)}")
    coll_bytes = g("serving_collective_bytes_total")
    if coll_bytes:
        lines.append(
            f" tp        collectives/quantum "
            f"{g('serving_collective_count_total'):>4.0f} ops, "
            f"{coll_bytes / 1024.0:>9.1f} KiB")
    # cluster line — only once a router has placed traffic
    routed = _snap_sum(snapshot, "serving_router_requests_total")
    if routed:
        m = _snap_metric(snapshot, "serving_router_requests_total")
        by_reason = {}
        for s in m["series"]:
            r = s.get("labels", {}).get("reason", "?")
            by_reason[r] = by_reason.get(r, 0.0) + s.get("value", 0.0)
        hit_rate = _snap_sum(snapshot, "serving_router_affinity_hit_rate")
        handoffs = _snap_sum(snapshot, "serving_router_handoffs_total")
        lines.append(
            f" cluster   routed {routed:>5.0f} "
            f"(aff {by_reason.get('affinity', 0):>4.0f}, "
            f"bal {by_reason.get('balance', 0):>4.0f}, "
            f"fo {by_reason.get('failover', 0):>3.0f})  "
            f"handoffs {handoffs:>3.0f}  hit {hit_rate:6.1%}")
        host_gap = _snap_sum(snapshot, "serving_host_gap_fraction")
        if host_gap:
            lines.append(
                f" host gap  {host_gap:6.1%} of decode dispatch wall "
                f"spent host-side (multi-quantum collapses this)")
    lines.append(bar)
    return "\n".join(lines) + "\n"
