"""Chrome trace-event recording — per-request lifecycle spans the
serving engine emits at quantum/step boundaries, exported as the JSON
object format Perfetto / chrome://tracing load directly (reference:
the chrome-trace exporter of the paddle profiler,
``python/paddle/profiler/profiler.py`` — unverified, SURVEY.md §0; the
event schema is the Trace Event Format's ``X``/``i``/``C``/``M``
phases).

Hot-path-safe by construction: recording one event is an epoch
subtraction plus one ``list.append`` into a BOUNDED buffer — when
``max_events`` is reached new events are counted as dropped instead of
growing the buffer (the drop counter is exported in the trace
metadata), and nothing here imports jax or touches device values.

Timestamps are microseconds relative to the recorder's epoch
(``time.perf_counter`` at construction), so traces start near t=0 and
the engine can pass through the very ``perf_counter`` stamps it
already takes at step boundaries.
"""
from __future__ import annotations

import json
import time

__all__ = ["TraceRecorder", "validate_chrome_trace",
           "load_chrome_trace"]

_PID = 1  # single-process traces: one pid, tracks are tids


class TraceRecorder:
    """Bounded trace-event buffer.

    Event kinds (all take ``t``/``t0``/``t1`` as perf_counter seconds,
    converted to epoch-relative µs):

    - :meth:`complete` — an ``X`` span (name, start, duration).
    - :meth:`instant` — an ``i`` thread-scoped marker.
    - :meth:`counter` — a ``C`` sampled-values track (dict of series).
    - :meth:`thread_name` — an ``M`` metadata record naming a track.
    """

    def __init__(self, max_events=65536, epoch=None):
        self.max_events = int(max_events)
        self.epoch = time.perf_counter() if epoch is None else float(epoch)
        self.events = []
        self.dropped = 0
        self._named = set()

    def __len__(self):
        return len(self.events)

    def _us(self, t):
        return round((float(t) - self.epoch) * 1e6, 3)

    def _push(self, ev):
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append(ev)

    def thread_name(self, tid, name):
        """Name a track (idempotent)."""
        if tid in self._named:
            return
        self._named.add(tid)
        self._push({"name": "thread_name", "ph": "M", "pid": _PID,
                    "tid": int(tid), "args": {"name": str(name)}})

    def complete(self, name, t0, t1, tid=0, args=None):
        ev = {"name": str(name), "ph": "X", "pid": _PID,
              "tid": int(tid), "ts": self._us(t0),
              "dur": max(round((float(t1) - float(t0)) * 1e6, 3), 0.0)}
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    def instant(self, name, t, tid=0, args=None):
        ev = {"name": str(name), "ph": "i", "s": "t", "pid": _PID,
              "tid": int(tid), "ts": self._us(t)}
        if args:
            ev["args"] = dict(args)
        self._push(ev)

    def counter(self, name, t, values, tid=0):
        self._push({"name": str(name), "ph": "C", "pid": _PID,
                    "tid": int(tid), "ts": self._us(t),
                    "args": {k: float(v) for k, v in values.items()}})

    # -- export ------------------------------------------------------------
    def chrome_trace(self):
        """The JSON Object Format: ``traceEvents`` + metadata.
        Events sorted by (ts, tid) — loaders do not require order, but
        determinism keeps golden comparisons byte-stable."""
        evs = sorted(self.events,
                     key=lambda e: (e.get("ts", -1.0), e["tid"],
                                    e["name"]))
        return {
            "traceEvents": evs,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "paddle_tpu.obs",
                "dropped_events": self.dropped,
            },
        }

    def save(self, path):
        obj = self.chrome_trace()
        validate_chrome_trace(obj)
        with open(path, "w") as f:
            json.dump(obj, f, sort_keys=True)
        return path


_REQUIRED_BY_PHASE = {
    "X": ("ts", "dur"),
    "i": ("ts",),
    "C": ("ts", "args"),
    "M": ("args",),
}


def validate_chrome_trace(obj):
    """Schema check for the subset of the Trace Event Format this
    recorder emits; raises ValueError with the first offending event.
    Used by :meth:`TraceRecorder.save`, the CLI, and the round-trip
    test."""
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError("not a chrome trace: missing 'traceEvents'")
    for i, ev in enumerate(obj["traceEvents"]):
        ctx = f"traceEvents[{i}] = {ev!r}"
        for k in ("name", "ph", "pid", "tid"):
            if k not in ev:
                raise ValueError(f"{ctx}: missing {k!r}")
        ph = ev["ph"]
        if ph not in _REQUIRED_BY_PHASE:
            raise ValueError(f"{ctx}: unsupported phase {ph!r}")
        for k in _REQUIRED_BY_PHASE[ph]:
            if k not in ev:
                raise ValueError(f"{ctx}: phase {ph!r} missing {k!r}")
        if "ts" in ev and (not isinstance(ev["ts"], (int, float))
                           or ev["ts"] < 0):
            raise ValueError(f"{ctx}: ts must be a non-negative number")
        if ph == "X" and (not isinstance(ev["dur"], (int, float))
                          or ev["dur"] < 0):
            raise ValueError(f"{ctx}: dur must be a non-negative number")
        if ph == "i" and ev.get("s", "t") not in ("t", "p", "g"):
            raise ValueError(f"{ctx}: instant scope must be t|p|g")
    return obj


def load_chrome_trace(path):
    """Load + validate a saved trace; returns the dict."""
    with open(path) as f:
        obj = json.load(f)
    return validate_chrome_trace(obj)
