"""Serving SLOs: declarative objectives evaluated with Google-SRE
multi-window burn rates over the obs layer's host ring-buffer series
(reference: the serving-health surface of the reference's deployed
predictor services — §2.6/§3.5's AnalysisPredictor/Predictor.run run as
an *operated* service, not a library loop — unverified, SURVEY.md §0;
the burn-rate policy itself is the SRE-workbook multiwindow,
multi-burn-rate alerting recipe).

An :class:`SLO` states an objective over one serving signal:

- **latency objectives** (``ttft_seconds``, ``e2e_latency_seconds``,
  ``inter_token_seconds``): "``target`` of requests complete under
  ``threshold`` seconds" — e.g. p95 TTFT < 500 ms is
  ``SLO("ttft_p95", "ttft_seconds", threshold=0.5, target=0.95)``.
- **rate objectives** (``request_outcomes``): "``target`` of requests
  finish well" — the series records 1.0 for a bad outcome (shed /
  error) and 0.0 for a good one, so the bad fraction IS the rate.

Evaluation is the SRE burn rate: with error budget ``1 - target``,
``burn = bad_fraction / budget`` — burn 1.0 spends the budget exactly
at the objective's horizon, burn 10 spends it 10x faster. Each SLO is
evaluated over TWO trailing windows (fast, default 5 min; slow,
default 1 h) of the per-request sample series
:meth:`~paddle_tpu.obs.serving.ServingObs.timeseries` keeps on the
host, and the health state is gated on BOTH windows agreeing —
``CRITICAL`` when both burn at ``critical_burn``, ``WARN`` when both
burn at ``warn_burn`` — so a brief spike (fast hot, slow cold) or a
long-ago incident (slow hot, fast cold) does not flap the state.

States are totally ordered ``OK < WARN < CRITICAL``
(:class:`HealthState`); an :class:`SLOSet` evaluates many objectives
and reports the worst, as a machine-readable dict the exporter's
``/healthz`` / ``/slo`` endpoints (obs/export.py) and the serving
front door's load-shedding admission
(:class:`paddle_tpu.serving.FrontDoorPolicy`) consume directly.

Edge semantics, unit-tested (tests/test_slo.py): an EMPTY window burns
nothing (no traffic is not an outage — n=0, burn 0.0, OK), and
CLOCK-SKEWED samples stamped in the future count as "now" in every
window instead of being silently dropped.

Everything here is host-side python over plain lists — no jax imports,
nothing that can leak into a trace.
"""
from __future__ import annotations

import time

__all__ = [
    "HealthState", "OK", "WARN", "CRITICAL", "state_of", "worst_state",
    "SLO", "SLOSet", "default_serving_slos",
    "LATENCY_SIGNALS", "RATE_SIGNALS",
]

# signal name == the ServingObs sample-series key it evaluates
LATENCY_SIGNALS = ("ttft_seconds", "e2e_latency_seconds",
                   "inter_token_seconds")
RATE_SIGNALS = ("request_outcomes",)


class HealthState:
    """One of the ordered health states ``OK < WARN < CRITICAL``.

    Compares against other states or their lowercase string names, so
    report consumers can write ``state >= "warn"`` without importing
    the singletons; ``str()`` is the JSON form."""

    __slots__ = ("name", "rank")

    def __init__(self, name, rank):
        self.name = str(name)
        self.rank = int(rank)

    @staticmethod
    def _rank_of(other):
        if isinstance(other, HealthState):
            return other.rank
        if isinstance(other, str):
            return state_of(other).rank
        return None

    def __eq__(self, other):
        r = self._rank_of(other)
        return NotImplemented if r is None else self.rank == r

    def __lt__(self, other):
        r = self._rank_of(other)
        return NotImplemented if r is None else self.rank < r

    def __le__(self, other):
        r = self._rank_of(other)
        return NotImplemented if r is None else self.rank <= r

    def __gt__(self, other):
        r = self._rank_of(other)
        return NotImplemented if r is None else self.rank > r

    def __ge__(self, other):
        r = self._rank_of(other)
        return NotImplemented if r is None else self.rank >= r

    def __hash__(self):
        return hash(self.rank)

    def __str__(self):
        return self.name

    def __repr__(self):
        return self.name


OK = HealthState("ok", 0)
WARN = HealthState("warn", 1)
CRITICAL = HealthState("critical", 2)
_STATES = {s.name: s for s in (OK, WARN, CRITICAL)}


def state_of(name):
    """Parse a state name back to its :class:`HealthState` singleton
    (the inverse of the report's ``str()`` form)."""
    if isinstance(name, HealthState):
        return name
    try:
        return _STATES[str(name).lower()]
    except KeyError:
        raise ValueError(
            f"unknown health state {name!r}; expected one of "
            f"{sorted(_STATES)}")


def worst_state(states):
    """The max of an iterable of states/names; OK when empty."""
    out = OK
    for s in states:
        s = state_of(s)
        if s > out:
            out = s
    return out


class SLO:
    """One declarative objective over one serving signal.

    Args:
        name: report key (e.g. ``ttft_p95``); unique within a set.
        signal: sample series to evaluate — one of
            :data:`LATENCY_SIGNALS` or :data:`RATE_SIGNALS`.
        threshold: latency objectives only — the per-request bound in
            seconds; a sample above it is "bad".
        target: fraction of requests that must be good (0.95 == "p95
            under threshold"); the error budget is ``1 - target``.
        fast_window / slow_window: trailing evaluation windows in
            seconds (SRE defaults: 5 min / 1 h).
        warn_burn / critical_burn: burn-rate gates; BOTH windows must
            exceed a gate for the state to escalate.
    """

    def __init__(self, name, signal, threshold=None, target=0.95,
                 fast_window=300.0, slow_window=3600.0,
                 warn_burn=3.0, critical_burn=10.0):
        self.name = str(name)
        self.signal = str(signal)
        if self.signal not in LATENCY_SIGNALS + RATE_SIGNALS:
            raise ValueError(
                f"SLO {name!r}: unknown signal {signal!r}; expected one "
                f"of {LATENCY_SIGNALS + RATE_SIGNALS}")
        self.is_rate = self.signal in RATE_SIGNALS
        if self.is_rate:
            if threshold is not None:
                raise ValueError(
                    f"SLO {name!r}: rate signal {signal!r} takes no "
                    f"threshold (the series already records good/bad)")
            self.threshold = None
        else:
            if threshold is None or float(threshold) <= 0:
                raise ValueError(
                    f"SLO {name!r}: latency signal {signal!r} needs a "
                    f"positive threshold in seconds, got {threshold!r}")
            self.threshold = float(threshold)
        self.target = float(target)
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO {name!r}: target must be in (0, 1), got {target}")
        self.budget = 1.0 - self.target
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        if not 0.0 < self.fast_window < self.slow_window:
            raise ValueError(
                f"SLO {name!r}: need 0 < fast_window < slow_window, got "
                f"{fast_window} / {slow_window}")
        self.warn_burn = float(warn_burn)
        self.critical_burn = float(critical_burn)
        if not 0.0 < self.warn_burn <= self.critical_burn:
            raise ValueError(
                f"SLO {name!r}: need 0 < warn_burn <= critical_burn, "
                f"got {warn_burn} / {critical_burn}")

    def _is_bad(self, value):
        if self.is_rate:
            return float(value) >= 0.5  # outcome series: 1.0 == bad
        return float(value) > self.threshold

    def window_stats(self, samples, now, window):
        """(n, bad, bad_fraction, burn_rate) over one trailing window
        of ``(t, value)`` samples. A sample stamped in the FUTURE
        (clock skew across threads/hosts) has its age clamped to 0 so
        it counts in every window rather than silently vanishing; an
        empty window burns nothing."""
        n = bad = 0
        for t, v in samples:
            age = now - float(t)
            if age < 0.0:
                age = 0.0
            if age <= window:
                n += 1
                if self._is_bad(v):
                    bad += 1
        frac = (bad / n) if n else 0.0
        return {
            "window_s": window, "n": n, "bad": bad,
            "bad_fraction": frac, "burn_rate": frac / self.budget,
        }

    def evaluate(self, series, now=None):
        """Burn-rate report for this objective over a series dict
        (``{signal: [(t, value), ...]}`` — live
        :meth:`ServingObs.timeseries` output or a saved snapshot's
        lists). ``now`` defaults to the obs clock
        (``time.perf_counter``); pass the snapshot's stamp when
        evaluating offline."""
        if now is None:
            now = time.perf_counter()
        samples = series.get(self.signal, ())
        fast = self.window_stats(samples, now, self.fast_window)
        slow = self.window_stats(samples, now, self.slow_window)
        if (fast["burn_rate"] >= self.critical_burn
                and slow["burn_rate"] >= self.critical_burn):
            state = CRITICAL
        elif (fast["burn_rate"] >= self.warn_burn
                and slow["burn_rate"] >= self.warn_burn):
            state = WARN
        else:
            state = OK
        return {
            "name": self.name, "signal": self.signal,
            "state": str(state), "threshold": self.threshold,
            "target": self.target, "budget": self.budget,
            "warn_burn": self.warn_burn,
            "critical_burn": self.critical_burn,
            "windows": {"fast": fast, "slow": slow},
        }


class SLOSet:
    """An ordered set of objectives evaluated together; overall health
    is the WORST per-objective state. ``None`` builds
    :func:`default_serving_slos`."""

    def __init__(self, slos=None):
        self.slos = list(default_serving_slos() if slos is None
                         else slos)
        seen = set()
        for s in self.slos:
            if not isinstance(s, SLO):
                raise TypeError(f"SLOSet takes SLO instances, got {s!r}")
            if s.name in seen:
                raise ValueError(f"duplicate SLO name {s.name!r}")
            seen.add(s.name)

    def __iter__(self):
        return iter(self.slos)

    def __len__(self):
        return len(self.slos)

    def threshold(self, signal):
        """Tightest latency threshold declared for ``signal`` (None if
        no objective covers it) — the flight recorder's anomaly rule
        reads its dump triggers from here."""
        ts = [s.threshold for s in self.slos
              if s.signal == signal and s.threshold is not None]
        return min(ts) if ts else None

    def evaluate(self, source, now=None):
        """The machine-readable health report the exporter serves and
        the front door's shedding admission polls. ``source`` is a
        ServingObs (or
        anything with ``timeseries()``) or a plain series dict."""
        series = (source.timeseries() if hasattr(source, "timeseries")
                  else source)
        if now is None:
            now = time.perf_counter()
        objectives = [s.evaluate(series, now) for s in self.slos]
        state = worst_state(o["state"] for o in objectives)
        return {
            "version": 1,
            "state": str(state),
            "now": float(now),
            "objectives": objectives,
        }


def default_serving_slos(ttft_p95_s=0.5, inter_token_p99_s=0.1,
                         e2e_p99_s=30.0, error_budget=0.01, **kw):
    """The stock serving objective set: p95 TTFT, p99 inter-token, p99
    e2e latency, and a 99%-good outcome (error/shed) rate. Extra
    keyword args (windows, burn gates) forward to every
    :class:`SLO`."""
    return [
        SLO("ttft_p95", "ttft_seconds", threshold=ttft_p95_s,
            target=0.95, **kw),
        SLO("inter_token_p99", "inter_token_seconds",
            threshold=inter_token_p99_s, target=0.99, **kw),
        SLO("e2e_p99", "e2e_latency_seconds", threshold=e2e_p99_s,
            target=0.99, **kw),
        SLO("error_rate", "request_outcomes",
            target=1.0 - float(error_budget), **kw),
    ]
