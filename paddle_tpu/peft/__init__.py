"""paddle.peft-style parameter-efficient fine-tuning (reference:
paddlenlp.peft.lora — unverified, SURVEY.md §0).

TPU-native notes: LoRA is pure layer surgery — the frozen base weight
stays on whatever NamedSharding the fleet layers gave it, the low-rank
A/B factors are tiny and replicate, and the whole delta rides one XLA
fusion (x @ A @ B * scaling added to the base matmul's output). Under
`JittedTrainStep` the frozen params still travel as inputs; only the
LoRA params receive gradients (stop_gradient on everything else).
"""
from .lora import (  # noqa: F401
    LoRAConfig, LoRALinear, LoRAModel, get_lora_model,
    mark_only_lora_as_trainable, lora_state_dict,
)

__all__ = [
    "LoRAConfig", "LoRALinear", "LoRAModel", "get_lora_model",
    "mark_only_lora_as_trainable", "lora_state_dict",
]
