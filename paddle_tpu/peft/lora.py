"""LoRA — low-rank adaptation layers (reference: paddlenlp/peft/lora/
lora_layers.py + lora_model.py — unverified, SURVEY.md §0).

``y = x @ W + b + (x @ A) @ B * (alpha / r)`` with W frozen; A is
Gaussian-initialized, B zero-initialized so the adapted model starts
EXACTLY equal to the base model. ``merge()`` folds the delta into W for
zero-overhead inference; ``unmerge()`` restores it.
"""
from __future__ import annotations

import re

from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear
from ..nn import functional as F
from ..nn import initializer as I

__all__ = [
    "LoRAConfig", "LoRALinear", "LoRAModel", "get_lora_model",
    "mark_only_lora_as_trainable", "lora_state_dict",
]


class LoRAConfig:
    """Mirrors the reference's LoRAConfig fields that matter here.

    Args:
        r: rank of the update matrices.
        lora_alpha: scaling numerator (delta is scaled by alpha / r).
        lora_dropout: dropout on the LoRA input path (train only).
        target_modules: list of regex patterns matched against sublayer
            NAMES (e.g. ``[".*q_proj", ".*v_proj"]``); every matching
            ``Linear``-like layer is wrapped.
        trainable_bias: also leave biases of wrapped layers trainable.
    """

    def __init__(self, r=8, lora_alpha=16, lora_dropout=0.0,
                 target_modules=(".*q_proj", ".*v_proj"),
                 trainable_bias=False):
        if r < 1:
            raise ValueError(f"LoRA rank must be >= 1, got {r}")
        self.r = int(r)
        self.lora_alpha = float(lora_alpha)
        self.lora_dropout = float(lora_dropout)
        self.target_modules = list(target_modules)
        self.trainable_bias = bool(trainable_bias)


class LoRALinear(Layer):
    """A Linear (or fleet Column/RowParallelLinear) wrapped with a
    low-rank delta. The base layer keeps its own (possibly mp-sharded)
    weight, frozen; A/B are small replicated factors."""

    def __init__(self, base, r, lora_alpha, lora_dropout=0.0):
        super().__init__()
        w = base.weight
        in_features, out_features = int(w.shape[0]), int(w.shape[1])
        self.base = base
        self.r = int(r)
        self.scaling = float(lora_alpha) / float(r)
        self.lora_dropout = float(lora_dropout)
        # reference init: A ~ N(0, 1/r) i.e. std = sqrt(1/r)
        # (kaiming-ish), B = 0 → the adapted forward starts bit-equal
        # to the base forward. (ADVICE round-5 low: std=1.0/r gave
        # variance 1/r², shrinking adapter updates as r grew.)
        self.lora_A = self.create_parameter(
            (in_features, self.r),
            default_initializer=I.Normal(std=(1.0 / self.r) ** 0.5))
        self.lora_B = self.create_parameter(
            (self.r, out_features), default_initializer=I.Constant(0.0))
        self._merged = False
        base.weight.stop_gradient = True
        if getattr(base, "bias", None) is not None:
            base.bias.stop_gradient = True

    def forward(self, x):
        out = self.base(x)
        if self._merged:
            return out
        h = x
        if self.lora_dropout and self.training:
            h = F.dropout(h, p=self.lora_dropout, training=True)
        delta = F.linear(F.linear(h, self.lora_A), self.lora_B)
        return out + delta * self.scaling

    def merge(self):
        """Fold A@B*scaling into the frozen base weight (inference)."""
        if self._merged:
            return self
        w = self.base.weight
        w._value = (w._value
                    + (self.lora_A._value @ self.lora_B._value
                       * self.scaling).astype(w._value.dtype))
        self._merged = True
        return self

    def unmerge(self):
        if not self._merged:
            return self
        w = self.base.weight
        w._value = (w._value
                    - (self.lora_A._value @ self.lora_B._value
                       * self.scaling).astype(w._value.dtype))
        self._merged = False
        return self

    def extra_repr(self):
        return f"r={self.r}, scaling={self.scaling}, merged={self._merged}"


def _is_linear_like(layer):
    from ..distributed.fleet.layers.mpu.mp_layers import (
        ColumnParallelLinear, RowParallelLinear,
    )
    from ..nn.quant import QuantizedLinear

    if isinstance(layer, QuantizedLinear):
        # previously this fell through duck-typing and the quantized
        # layer was silently skipped — name a target, get an answer
        raise ValueError(
            "LoRA target matched a QuantizedLinear base: QLoRA-style "
            "adapters over int8 bases are not implemented — the low-"
            "rank delta would train against the dequantized weight "
            "while merge() cannot fold a float delta into an int8 "
            "weight without requantization error. Apply LoRA BEFORE "
            "PTQ convert (then quantize the merged model), or exclude "
            "quantized layers from target_modules.")
    return isinstance(layer, (Linear, ColumnParallelLinear,
                              RowParallelLinear)) and \
        getattr(layer, "weight", None) is not None


def _wrap_targets(model, config):
    pats = [re.compile(p) for p in config.target_modules]
    wrapped = []

    def visit(layer, prefix):
        for name, sub in list(layer._sub_layers.items()):
            full = f"{prefix}.{name}" if prefix else name
            if any(p.fullmatch(full) or p.fullmatch(name) for p in pats) \
                    and _is_linear_like(sub):
                lora = LoRALinear(sub, config.r, config.lora_alpha,
                                  config.lora_dropout)
                layer._sub_layers[name] = lora
                wrapped.append(full)
            else:
                visit(sub, full)

    visit(model, "")
    if not wrapped:
        raise ValueError(
            f"LoRA target_modules {config.target_modules} matched no "
            f"Linear-like sublayer — check the patterns against "
            f"named_sublayers()")
    return wrapped


def mark_only_lora_as_trainable(model, trainable_bias=False):
    """Freeze every param except lora_A/lora_B; with ``trainable_bias``
    the biases of WRAPPED layers (the LoRALinear bases) stay trainable
    too — not every bias model-wide, and the adapter state dict must
    then include them (see lora_state_dict)."""
    for name, p in model.named_parameters():
        is_lora = "lora_A" in name or "lora_B" in name
        is_wrapped_bias = (trainable_bias and name.endswith(".bias")
                           and ".base." in name)
        p.stop_gradient = not (is_lora or is_wrapped_bias)
    return model


def lora_state_dict(model):
    """The adapter artifact (reference: lora_model_state.pdparams):
    lora_A/lora_B plus any TRAINABLE wrapped-layer bias (the
    trainable_bias=True case) — everything a reload onto a fresh base
    needs to reproduce the trained model."""
    out = {}
    for name, p in model.state_dict().items():
        if "lora_A" in name or "lora_B" in name:
            out[name] = p
    for name, p in model.named_parameters():
        if (name.endswith(".bias") and ".base." in name
                and not p.stop_gradient):
            out[name] = p
    return out


class LoRAModel(Layer):
    """Wrapper mirroring paddlenlp.peft.LoRAModel: wraps target modules
    in-place, freezes the rest, and forwards transparently."""

    def __init__(self, model, lora_config):
        super().__init__()
        self.lora_config = lora_config
        self.wrapped_names = _wrap_targets(model, lora_config)
        self.add_sublayer("model", model)
        mark_only_lora_as_trainable(self,
                                    lora_config.trainable_bias)

    def forward(self, *args, **kwargs):
        return self.model(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(super().__getattr__("model"), name)

    def merge(self):
        for layer in self._lora_layers():
            layer.merge()
        return self

    def unmerge(self):
        for layer in self._lora_layers():
            layer.unmerge()
        return self

    def _lora_layers(self):
        out = []

        def visit(layer):
            for sub in layer._sub_layers.values():
                if isinstance(sub, LoRALinear):
                    out.append(sub)
                visit(sub)

        visit(self)
        return out


def get_lora_model(model, lora_config):
    """Reference entry point: paddlenlp.peft.get_lora_model."""
    return LoRAModel(model, lora_config)
