"""paddle.distribution — probability distributions (reference:
python/paddle/distribution/ — unverified, SURVEY.md §0).

Built on jax.random / jax.scipy.stats through the dispatch seam:
``log_prob``/``entropy``/``kl_divergence`` are differentiable taped ops;
``sample`` draws from the framework RNG (``paddle.seed`` determinism);
``rsample`` is the reparameterized (pathwise-differentiable) form where
one exists.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.random import next_key
from ..tensor._helpers import apply, ensure_tensor

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
    "Beta", "Dirichlet", "Laplace", "Gumbel", "LogNormal",
    "kl_divergence", "register_kl",
]


def _shape_of(sample_shape, *params):
    base = jnp.broadcast_shapes(*[jnp.shape(p) for p in params])
    return tuple(sample_shape) + base


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return self.log_prob(value).exp()

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = ensure_tensor(loc, dtype="float32")
        self.scale = ensure_tensor(scale, dtype="float32")

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    def rsample(self, shape=()):
        key = next_key()
        shp = _shape_of(shape, self.loc._value, self.scale._value)
        return apply(
            lambda m, s: m + s * jax.random.normal(key, shp),
            self.loc, self.scale, op_name="normal_rsample",
        )

    sample = rsample

    def log_prob(self, value):
        value = ensure_tensor(value)
        return apply(
            lambda v, m, s: jax.scipy.stats.norm.logpdf(v, m, s),
            value, self.loc, self.scale, op_name="normal_log_prob",
        )

    def entropy(self):
        return apply(
            lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
            self.scale, op_name="normal_entropy",
        )


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self._base = Normal(loc, scale)
        self.loc = self._base.loc
        self.scale = self._base.scale

    def rsample(self, shape=()):
        return self._base.rsample(shape).exp()

    sample = rsample

    def log_prob(self, value):
        value = ensure_tensor(value)
        return self._base.log_prob(value.log()) - value.log()

    def entropy(self):
        return self._base.entropy() + self.loc


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = ensure_tensor(low, dtype="float32")
        self.high = ensure_tensor(high, dtype="float32")

    def rsample(self, shape=()):
        key = next_key()
        shp = _shape_of(shape, self.low._value, self.high._value)
        return apply(
            lambda lo, hi: lo + (hi - lo) * jax.random.uniform(key, shp),
            self.low, self.high, op_name="uniform_rsample",
        )

    sample = rsample

    def log_prob(self, value):
        value = ensure_tensor(value)
        return apply(
            lambda v, lo, hi: jnp.where(
                (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf
            ),
            value, self.low, self.high, op_name="uniform_log_prob",
        )

    def entropy(self):
        return (self.high - self.low).log()


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = ensure_tensor(loc, dtype="float32")
        self.scale = ensure_tensor(scale, dtype="float32")

    def rsample(self, shape=()):
        key = next_key()
        shp = _shape_of(shape, self.loc._value, self.scale._value)
        return apply(
            lambda m, s: m + s * jax.random.laplace(key, shp),
            self.loc, self.scale, op_name="laplace_rsample",
        )

    sample = rsample

    def log_prob(self, value):
        value = ensure_tensor(value)
        return apply(
            lambda v, m, s: -jnp.abs(v - m) / s - jnp.log(2 * s),
            value, self.loc, self.scale, op_name="laplace_log_prob",
        )

    def entropy(self):
        return 1.0 + (2.0 * self.scale).log()


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = ensure_tensor(loc, dtype="float32")
        self.scale = ensure_tensor(scale, dtype="float32")

    def rsample(self, shape=()):
        key = next_key()
        shp = _shape_of(shape, self.loc._value, self.scale._value)
        return apply(
            lambda m, s: m + s * jax.random.gumbel(key, shp),
            self.loc, self.scale, op_name="gumbel_rsample",
        )

    sample = rsample

    def log_prob(self, value):
        value = ensure_tensor(value)

        def fn(v, m, s):
            z = (v - m) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)

        return apply(fn, value, self.loc, self.scale,
                     op_name="gumbel_log_prob")

    def entropy(self):
        return self.scale.log() + (1.0 + float(np.euler_gamma))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("Bernoulli: pass exactly one of probs/logits")
        if probs is not None:
            self.probs = ensure_tensor(probs, dtype="float32")
        else:
            self.probs = ensure_tensor(logits, dtype="float32").sigmoid()

    def sample(self, shape=()):
        key = next_key()
        shp = _shape_of(shape, self.probs._value)
        return apply(
            lambda p: jax.random.bernoulli(key, p, shp).astype(jnp.float32),
            self.probs, op_name="bernoulli_sample",
        )

    def log_prob(self, value):
        value = ensure_tensor(value)
        eps = 1e-7

        def fn(v, p):
            p = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

        return apply(fn, value, self.probs, op_name="bernoulli_log_prob")

    def entropy(self):
        eps = 1e-7

        def fn(p):
            p = jnp.clip(p, eps, 1 - eps)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

        return apply(fn, self.probs, op_name="bernoulli_entropy")


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = ensure_tensor(logits, dtype="float32")

    @property
    def probs(self):
        return apply(
            lambda l: jax.nn.softmax(l, axis=-1), self.logits,
            op_name="categorical_probs",
        )

    def sample(self, shape=()):
        key = next_key()
        return apply(
            lambda l: jax.random.categorical(
                key, l, shape=tuple(shape) + l.shape[:-1]
            ),
            self.logits, op_name="categorical_sample",
        )

    def log_prob(self, value):
        value = ensure_tensor(value)

        def fn(l, v):
            logp = jax.nn.log_softmax(l, axis=-1)
            return jnp.take_along_axis(
                logp, v[..., None].astype(jnp.int32), axis=-1
            )[..., 0]

        return apply(fn, self.logits, value, op_name="categorical_log_prob")

    def entropy(self):
        def fn(l):
            logp = jax.nn.log_softmax(l, axis=-1)
            return -(jnp.exp(logp) * logp).sum(-1)

        return apply(fn, self.logits, op_name="categorical_entropy")


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = ensure_tensor(alpha, dtype="float32")
        self.beta = ensure_tensor(beta, dtype="float32")

    def sample(self, shape=()):
        key = next_key()
        shp = _shape_of(shape, self.alpha._value, self.beta._value)
        return apply(
            lambda a, b: jax.random.beta(key, a, b, shp),
            self.alpha, self.beta, op_name="beta_sample",
        )

    def log_prob(self, value):
        value = ensure_tensor(value)
        return apply(
            lambda v, a, b: jax.scipy.stats.beta.logpdf(v, a, b),
            value, self.alpha, self.beta, op_name="beta_log_prob",
        )

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = ensure_tensor(concentration, dtype="float32")

    def sample(self, shape=()):
        key = next_key()
        return apply(
            lambda c: jax.random.dirichlet(key, c, tuple(shape)),
            self.concentration, op_name="dirichlet_sample",
        )

    def log_prob(self, value):
        value = ensure_tensor(value)
        return apply(
            lambda v, c: jax.scipy.stats.dirichlet.logpdf(v.T, c),
            value, self.concentration, op_name="dirichlet_log_prob",
        )


# -- KL divergence registry ---------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence not registered for "
            f"({type(p).__name__}, {type(q).__name__})"
        )
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def fn(m1, s1, m2, s2):
        var_ratio = (s1 / s2) ** 2
        t1 = ((m1 - m2) / s2) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))

    return apply(fn, p.loc, p.scale, q.loc, q.scale, op_name="kl_normal")


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def fn(pl, ph, ql, qh):
        inside = (ql <= pl) & (ph <= qh)
        return jnp.where(
            inside, jnp.log((qh - ql) / (ph - pl)), jnp.inf
        )

    return apply(fn, p.low, p.high, q.low, q.high, op_name="kl_uniform")


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    def fn(lp, lq):
        a = jax.nn.log_softmax(lp, axis=-1)
        b = jax.nn.log_softmax(lq, axis=-1)
        return (jnp.exp(a) * (a - b)).sum(-1)

    return apply(fn, p.logits, q.logits, op_name="kl_categorical")


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def fn(pp, qq):
        eps = 1e-7
        pp = jnp.clip(pp, eps, 1 - eps)
        qq = jnp.clip(qq, eps, 1 - eps)
        return pp * jnp.log(pp / qq) + (1 - pp) * jnp.log(
            (1 - pp) / (1 - qq))

    return apply(fn, p.probs, q.probs, op_name="kl_bernoulli")
