"""FLAGS registry.

The reference exposes ~200 gflags-style knobs settable via env (``FLAGS_*``)
or ``paddle.set_flags()`` (reference: paddle/phi/core/flags.cc, pybind
global_value_getter_setter — unverified, SURVEY.md §0). Here flags are a
plain registry with env-var override at first read; unknown flags may be
registered lazily so user code that sets vendor flags doesn't crash.
"""
from __future__ import annotations

import os
from typing import Any

__all__ = ["define_flag", "set_flags", "get_flags"]

_FLAGS: dict[str, Any] = {}
_HELP: dict[str, str] = {}


def _coerce(value, like):
    if isinstance(like, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(like, int) and not isinstance(like, bool):
        return int(value)
    if isinstance(like, float):
        return float(value)
    return value


def define_flag(name: str, default, help: str = ""):
    """Register a flag; env var of the same name wins over the default."""
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    value = default
    if name in os.environ:
        value = _coerce(os.environ[name], default)
    _FLAGS[name] = value
    _HELP[name] = help
    return value


def set_flags(flags: dict):
    """paddle.set_flags({'FLAGS_...': value})."""
    for k, v in flags.items():
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        if k in _FLAGS and _FLAGS[k] is not None:
            v = _coerce(v, _FLAGS[k])
        _FLAGS[k] = v


def get_flags(flags) -> dict:
    """paddle.get_flags('FLAGS_x') or (['FLAGS_x', ...])."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        out[k] = _FLAGS.get(k)
    return out


# Core flags (subset of the reference's set that has meaning here).
define_flag("FLAGS_check_nan_inf", False, "per-op NaN/Inf scan in eager mode")
define_flag("FLAGS_eager_delete_tensor_gb", 0.0, "accepted for compat; XLA manages memory")
define_flag("FLAGS_use_pallas_kernels", True, "route hot ops to Pallas kernels on TPU")
define_flag("FLAGS_pallas_force", False,
            "route to Pallas kernels even off-TPU (interpret mode; for tests)")
define_flag("FLAGS_allocator_strategy", "auto_growth", "accepted for compat")
define_flag("FLAGS_cudnn_deterministic", False, "accepted for compat; XLA is deterministic")
define_flag("FLAGS_embedding_deterministic", False, "accepted for compat")
