"""Dtype model for paddle_tpu.

Mirrors the reference's dtype surface (paddle.float32 etc.; reference:
paddle/phi/common/data_type.h — unverified path, see SURVEY.md §0) on top of
JAX/numpy dtypes. A ``DType`` is a thin, hashable wrapper around a canonical
``jnp.dtype`` that stringifies the paddle way (``paddle.float32``).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = [
    "DType",
    "dtype",
    "to_jax_dtype",
    "to_paddle_dtype",
    "get_default_dtype",
    "set_default_dtype",
    "finfo",
    "iinfo",
]


class DType:
    """A paddle-flavored dtype handle; interns one instance per name."""

    _registry: dict[str, "DType"] = {}

    def __new__(cls, name: str):
        if name in cls._registry:
            return cls._registry[name]
        self = super().__new__(cls)
        self._name = name
        self._np = np.dtype(name)
        cls._registry[name] = self
        return self

    @property
    def name(self) -> str:
        return self._name

    @property
    def numpy_dtype(self) -> np.dtype:
        return self._np

    def __repr__(self):
        return f"paddle.{self._name}"

    __str__ = __repr__

    def __hash__(self):
        return hash(self._name)

    def __eq__(self, other):
        if isinstance(other, DType):
            return self._name == other._name
        try:
            return np.dtype(_name_of(other)) == self._np
        except TypeError:
            return NotImplemented

    @property
    def is_floating_point(self) -> bool:
        return jnp.issubdtype(self._np, jnp.floating)

    @property
    def is_complex(self) -> bool:
        return jnp.issubdtype(self._np, jnp.complexfloating)

    @property
    def is_integer(self) -> bool:
        return jnp.issubdtype(self._np, jnp.integer)

    @property
    def itemsize(self) -> int:
        return self._np.itemsize


def _name_of(d) -> str:
    """Normalize any dtype-ish object to a canonical string name."""
    if isinstance(d, DType):
        return d._name
    if isinstance(d, str):
        # paddle accepts "float32", "fp32" style aliases
        aliases = {
            "fp32": "float32",
            "fp16": "float16",
            "bf16": "bfloat16",
            "fp64": "float64",
        }
        return aliases.get(d, d)
    if d is float:
        return "float32"
    if d is int:
        return "int64"
    if d is bool:
        return "bool"
    return np.dtype(d).name


# bfloat16 needs special-casing: np.dtype('bfloat16') works only because
# ml_dtypes registers it (jax always ships ml_dtypes).
bfloat16 = DType("bfloat16")
float16 = DType("float16")
float32 = DType("float32")
float64 = DType("float64")
int8 = DType("int8")
int16 = DType("int16")
int32 = DType("int32")
int64 = DType("int64")
uint8 = DType("uint8")
uint16 = DType("uint16")
uint32 = DType("uint32")
uint64 = DType("uint64")
bool_ = DType("bool")
complex64 = DType("complex64")
complex128 = DType("complex128")
float8_e4m3fn = DType("float8_e4m3fn")
float8_e5m2 = DType("float8_e5m2")


def dtype(d) -> DType:
    """Coerce to DType (paddle.dtype constructor analog)."""
    return DType(_name_of(d))


# With jax x64 disabled (the TPU-native default), 64-bit requests
# canonicalize down — silently, the way jax itself canonicalizes, instead
# of per-op truncation warnings. paddle's int64 indices become int32.
_CANONICAL = {
    "int64": "int32",
    "uint64": "uint32",
    "float64": "float32",
    "complex128": "complex64",
}


def to_jax_dtype(d):
    """DType/str/np.dtype → canonical jnp dtype (for use in jnp calls)."""
    if d is None:
        return None
    name = _name_of(d)
    import jax

    if not jax.config.jax_enable_x64:
        name = _CANONICAL.get(name, name)
    return jnp.dtype(name)


def to_paddle_dtype(d) -> DType:
    return DType(np.dtype(d).name)


_default_dtype = float32


def get_default_dtype() -> str:
    """Matches paddle.get_default_dtype(): returns the string name."""
    return _default_dtype.name


def set_default_dtype(d):
    global _default_dtype
    d = dtype(d)
    if not (d.is_floating_point or d.is_complex):
        raise TypeError(
            f"set_default_dtype only accepts floating dtypes, got {d}"
        )
    _default_dtype = d


def finfo(d):
    return jnp.finfo(to_jax_dtype(d))


def iinfo(d):
    return jnp.iinfo(to_jax_dtype(d))
