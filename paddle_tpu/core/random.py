"""Global RNG with paddle seed semantics on threaded JAX PRNG keys.

The reference keeps per-device generator state (paddle.seed, Generator;
reference: paddle/phi/core/generator.cc — unverified, SURVEY.md §0). Here a
``Generator`` is a (key, counter) pair: every random op draws
``fold_in(key, counter++)`` so eager calls are sequenced deterministically
after ``paddle.seed`` while each draw stays an independent stream — the
functional-JAX analog of advancing Philox offset state.
"""
from __future__ import annotations

import time

import jax
import numpy as np

__all__ = [
    "Generator",
    "seed",
    "default_generator",
    "next_key",
    "get_rng_state",
    "set_rng_state",
    "RNGStatesTracker",
    "get_rng_state_tracker",
]


class Generator:
    def __init__(self, seed_: int | None = None):
        if seed_ is None:
            seed_ = time.time_ns() % (2**31)
        self._seed = int(seed_)
        self._key = jax.random.PRNGKey(self._seed)
        self._counter = 0

    def manual_seed(self, seed_: int):
        self._seed = int(seed_)
        self._key = jax.random.PRNGKey(self._seed)
        self._counter = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        k = jax.random.fold_in(self._key, self._counter)
        self._counter += 1
        return k

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        seed_, counter = state
        self.manual_seed(seed_)
        self._counter = int(counter)


default_generator = Generator(0)

# While tracing (to_static / jitted train steps), random ops must draw from
# a TRACED key that enters the compiled program as an input — otherwise the
# mask freezes at trace time. ``traced_key_scope`` pushes such a key.
_traced_key_stack: list = []


class traced_key_scope:
    def __init__(self, key):
        self._key = key

    def __enter__(self):
        _traced_key_stack.append([self._key, 0])
        return self

    def __exit__(self, *exc):
        _traced_key_stack.pop()
        return False


def seed(value: int) -> Generator:
    """paddle.seed(v): reseed the global generator (and return it)."""
    return default_generator.manual_seed(value)


def next_key():
    if _traced_key_stack:
        entry = _traced_key_stack[-1]
        k = jax.random.fold_in(entry[0], entry[1])
        entry[1] += 1
        return k
    return default_generator.next_key()


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)


class RNGStatesTracker:
    """Named RNG streams for tensor-parallel dropout.

    Mirrors fleet's get_rng_state_tracker (reference:
    python/paddle/distributed/fleet/layers/mpu/random.py — unverified):
    ``local_seed`` streams differ per model-parallel rank (dropout masks
    differ across mp shards), ``global_seed`` streams agree.
    """

    def __init__(self):
        self._states: dict[str, Generator] = {}

    def add(self, name: str, seed_: int):
        if name in self._states:
            raise ValueError(f"rng state {name} already exists")
        self._states[name] = Generator(seed_)

    def reset(self):
        self._states = {}

    def states(self):
        return {k: g.get_state() for k, g in self._states.items()}

    def set_states(self, states):
        self._states = {}
        for k, s in states.items():
            g = Generator(0)
            g.set_state(s)
            self._states[k] = g

    class _Scope:
        def __init__(self, tracker, name):
            self.tracker, self.name = tracker, name

        def __enter__(self):
            self._saved = default_generator.get_state()
            g = self.tracker._states[self.name]
            default_generator.set_state(g.get_state())
            return self

        def __exit__(self, *exc):
            self.tracker._states[self.name].set_state(
                default_generator.get_state()
            )
            default_generator.set_state(self._saved)
            return False

    def rng_state(self, name: str = "global_seed"):
        if name not in self._states:
            self.add(name, np.random.randint(0, 2**31))
        return RNGStatesTracker._Scope(self, name)


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker
