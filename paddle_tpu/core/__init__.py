"""Core runtime: tensor, autograd, dispatch, dtype/place, RNG, flags."""
