"""Define-by-run autograd on a functional substrate.

The reference's eager engine wires generated GradNodes through AutogradMeta
and walks them queue-style in ``egr::Backward`` (reference:
paddle/fluid/eager/backward.cc — unverified, SURVEY.md §0). Here every
differentiable op records one ``Node`` holding a ``jax.vjp`` closure; the
forward runs exactly once (inside ``jax.vjp``), residuals live in the
closure, and ``backward()`` is a reverse-topological walk accumulating
cotangents. The whole tape is pure Python over jax values, so it works
identically on concrete arrays (eager) and tracers (inside ``jax.jit``).

Tensor *versions* are tracked with ``GradSlot`` objects: an in-place op
rebinds the Python Tensor to a fresh slot while recorded nodes keep
referencing the old version's slot — the functional analog of the
reference's inplace version counters, without their error cases.
"""
from __future__ import annotations

import functools
import weakref

import numpy as np
import jax

__all__ = [
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "GradSlot",
    "Node",
    "backward",
    "grad",
]

_grad_enabled = True


def is_grad_enabled() -> bool:
    return _grad_enabled


class _GradMode:
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = self._mode
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _GradMode(self._mode):
                return fn(*args, **kwargs)

        return wrapper


def no_grad(func=None):
    """paddle.no_grad: usable as context manager or decorator."""
    if func is not None:
        return _GradMode(False)(func)
    return _GradMode(False)


def enable_grad(func=None):
    if func is not None:
        return _GradMode(True)(func)
    return _GradMode(True)


class set_grad_enabled(_GradMode):
    pass


class GradSlot:
    """Identity of one tensor *version* in the autograd graph."""

    __slots__ = ("node", "owner_ref", "__weakref__")

    def __init__(self, owner=None, node=None):
        self.node = node  # producing Node, or None for leaves
        self.owner_ref = weakref.ref(owner) if owner is not None else None

    def owner(self):
        return self.owner_ref() if self.owner_ref is not None else None


class Node:
    """One recorded op: cotangents in → input cotangents out.

    ``closed``/``primals`` (the op as a pure function of its
    differentiable inputs, and those inputs' values) enable
    ``create_graph``: the backward walk can re-derive the VJP *through
    the dispatch seam* so the grad computation is itself taped."""

    __slots__ = ("vjp_fn", "inputs", "outputs", "treedef", "name",
                 "closed", "primals", "taped_vjp", "__weakref__")

    def __init__(self, vjp_fn, inputs, outputs, treedef, name="",
                 closed=None, primals=None, taped_vjp=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list[GradSlot] — the differentiable inputs
        self.outputs = outputs  # list[(GradSlot, shape, jnp_dtype)]
        self.treedef = treedef  # structure of the raw fn output
        self.name = name
        self.closed = closed
        self.primals = primals
        # create_graph path for ops whose VJP is user Python (PyLayer):
        # called with cotangent *Tensors*, returns grad Tensors recorded
        # on the tape
        self.taped_vjp = taped_vjp

    def __repr__(self):
        return f"<Node {self.name or 'op'} n_in={len(self.inputs)}>"


def _zero_cotangent(shape, dtype):
    import jax.numpy as jnp

    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    # Integer/bool outputs take symbolic-zero float0 cotangents.
    return np.zeros(shape, dtype=jax.dtypes.float0)


def _toposort(root_slots):
    """Topological order (producers first) over reachable Nodes."""
    order, seen = [], set()
    stack = [(s.node, False) for s in root_slots if s.node is not None]
    while stack:
        node, processed = stack.pop()
        if node is None:
            continue
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for s in node.inputs:
            if s.node is not None and id(s.node) not in seen:
                stack.append((s.node, False))
    return order


def _run_hooks(owner, g):
    from .tensor import Tensor

    if owner is None:
        return g
    for hook in owner._grad_hooks:
        h_in = g if isinstance(g, Tensor) else Tensor(g, stop_gradient=True)
        new_g = hook(h_in)
        if new_g is not None:
            g = new_g
    return g


def _replay_vjp(node, cots):
    """Re-derive ``node``'s VJP through the dispatch seam so the grad
    computation is recorded on the tape (create_graph=True path).

    The primal wrappers alias the *forward* slots, so second-order
    cotangents flow back into the original graph — d(grad)/d(x) sees the
    dependence of the residuals on x, which the stored ``vjp_fn``
    closure (constants baked in) cannot express."""
    from .tensor import Tensor
    from .dispatch import apply as dispatch_apply

    n_primal = len(node.primals)
    wrappers = []
    for slot, pv in zip(node.inputs, node.primals):
        w = Tensor(pv, stop_gradient=False)
        w._slot = slot
        wrappers.append(w)
    closed, treedef = node.closed, node.treedef

    def vjp_replay(*vals):
        pvs = vals[:n_primal]
        cvs = list(vals[n_primal:])
        _, vjp_fn = jax.vjp(closed, *pvs)
        return tuple(vjp_fn(jax.tree_util.tree_unflatten(treedef, cvs)))

    return dispatch_apply(
        vjp_replay, *wrappers, *cots,
        op_name=(node.name or "op") + "_grad",
    )


def backward(tensors, grad_tensors=None, retain_graph=False,
             create_graph=False, _grad_sink=None):
    """Run reverse accumulation from ``tensors``.

    Matches paddle.autograd.backward semantics: default cotangent is ones
    for scalar outputs; ``.grad`` is accumulated (+=) on leaves. With
    ``_grad_sink`` (a dict), grads are collected into the sink keyed by
    ``id(owner)`` instead of written to ``.grad`` — used by paddle.grad so
    it never pollutes ``.grad`` of uninvolved leaves.

    With ``create_graph=True`` every node's VJP is replayed through the
    dispatch seam, so the produced grads are themselves differentiable
    (reference: double-grad nodes in paddle/fluid/eager/ — unverified).
    """
    from .tensor import Tensor
    import jax.numpy as jnp

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    if create_graph:
        retain_graph = True

    cotangents: dict[int, object] = {}
    keepalive: dict[int, GradSlot] = {}

    def _deliver(owner, g):
        if _grad_sink is not None:
            oid = id(owner)
            _grad_sink[oid] = _grad_sink[oid] + g if oid in _grad_sink else g
        else:
            if isinstance(g, Tensor):
                g = g._value
            owner._set_grad_accum(g)

    def _accum(slot, g):
        sid = id(slot)
        keepalive[sid] = slot
        if sid in cotangents:
            cotangents[sid] = cotangents[sid] + g
        else:
            cotangents[sid] = g

    root_slots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError(
                "backward() called on a tensor with stop_gradient=True"
            )
        if g is None:
            # paddle fills the initial gradient with ones for roots of any
            # shape (grad_tensor=None semantics), not just scalars
            g = jnp.ones(t._value.shape, t._value.dtype)
            if create_graph:
                g = Tensor(g, stop_gradient=True)
        elif isinstance(g, Tensor):
            g = g if create_graph else g._value
        else:
            g = jnp.asarray(g)
        slot = t._ensure_slot()
        _accum(slot, g)
        root_slots.append(slot)

    order = _toposort(root_slots)

    for node in reversed(order):
        cots = []
        any_live = False
        for slot, shape, dt in node.outputs:
            g = cotangents.get(id(slot))
            owner = slot.owner()
            if g is None:
                g = _zero_cotangent(shape, dt)
            else:
                any_live = True
                g = _run_hooks(owner, g)
                if owner is not None and (
                    owner._retain_grad_flag and not owner.stop_gradient
                ):
                    _deliver(owner, g)
            cots.append(g)
        if not any_live or (node.vjp_fn is None and node.closed is None):
            continue
        if create_graph and node.closed is not None:
            in_grads = _replay_vjp(node, cots)
        elif create_graph and node.taped_vjp is not None:
            cot_t = [
                c if isinstance(c, Tensor) else Tensor(c, stop_gradient=True)
                for c in cots
            ]
            in_grads = node.taped_vjp(cot_t)
        elif create_graph:
            raise RuntimeError(
                f"create_graph=True cannot differentiate through "
                f"'{node.name or 'op'}' (no replayable forward recorded)"
            )
        else:
            cots = [c._value if isinstance(c, Tensor) else c for c in cots]
            cot_struct = jax.tree_util.tree_unflatten(node.treedef, cots)
            in_grads = node.vjp_fn(cot_struct)
        for slot, g in zip(node.inputs, in_grads):
            _accum(slot, g)
        if not retain_graph:
            node.vjp_fn = None  # free residuals eagerly
            node.closed = node.primals = None

    # Write .grad on leaves.
    for sid, slot in keepalive.items():
        if slot.node is None:
            owner = slot.owner()
            if owner is not None and not owner.stop_gradient:
                g = _run_hooks(owner, cotangents[sid])
                _deliver(owner, g)

    if not retain_graph:
        for slot in keepalive.values():
            owner = slot.owner()
            if owner is not None:
                owner._slot = None  # release graph


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
):
    """paddle.grad: grads of ``outputs`` w.r.t. ``inputs`` (always a list).

    ``create_graph=True`` returns grads that are themselves on the tape
    (the VJPs are replayed through the dispatch seam), so gradient-
    penalty losses compose: ``paddle.grad(..., create_graph=True)`` then
    ``loss.backward()``.
    """
    from .tensor import Tensor

    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    saved = [(t, t._retain_grad_flag) for t in inputs]
    for t in inputs:
        t._retain_grad_flag = True  # collect even if t is an intermediate
    sink: dict[int, object] = {}
    if retain_graph is None:
        retain_graph = create_graph
    try:
        backward(
            outputs, grad_outputs, retain_graph=bool(retain_graph),
            create_graph=create_graph, _grad_sink=sink,
        )
        results = []
        for t in inputs:
            g = sink.get(id(t))
            if g is None:
                if not allow_unused:
                    raise RuntimeError(
                        "one of the inputs was not used in the graph; pass "
                        "allow_unused=True to return None for it"
                    )
                results.append(None)
            elif isinstance(g, Tensor):
                results.append(g)  # create_graph: still on the tape
            else:
                results.append(Tensor(g, stop_gradient=True))
    finally:
        for t, flag in saved:
            t._retain_grad_flag = flag
    return results  # paddle.grad always returns a list
