"""Device/place model.

Mirrors the reference's Place hierarchy (phi::Place / CPUPlace / GPUPlace /
CustomPlace; reference: paddle/phi/common/place.h — unverified, SURVEY.md §0)
with a TPU-first twist: the accelerator place is ``TPUPlace`` and
``paddle.set_device('tpu')`` selects it. On machines without a TPU the
"tpu" place transparently maps to whatever jax's default backend is, so the
same user code runs under the CPU test mesh.
"""
from __future__ import annotations

import jax

__all__ = [
    "Place",
    "CPUPlace",
    "TPUPlace",
    "CUDAPlace",
    "XPUPlace",
    "CustomPlace",
    "set_device",
    "get_device",
    "device_for_place",
    "is_compiled_with_cuda",
    "is_compiled_with_xpu",
    "is_compiled_with_rocm",
    "is_compiled_with_custom_device",
]


class Place:
    """Base place: a named device slot (device_type, device_id)."""

    device_type = "undefined"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type == "tpu"


class CPUPlace(Place):
    device_type = "cpu"

    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "Place(cpu)"


class TPUPlace(Place):
    device_type = "tpu"


class CustomPlace(Place):
    """CustomDevice plugin seam (reference: paddle/phi/backends/custom/)."""

    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


# GPU/XPU places exist for API compatibility; they alias the accelerator.
class CUDAPlace(TPUPlace):
    pass


class XPUPlace(TPUPlace):
    pass


_current_place: Place | None = None


def _accelerator_devices():
    """Non-CPU jax devices, if any."""
    try:
        devs = jax.devices()
    except RuntimeError:
        return []
    return [d for d in devs if d.platform != "cpu"] or devs


def set_device(device) -> Place:
    """paddle.set_device('tpu' | 'cpu' | 'tpu:0')."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    name = str(device)
    if ":" in name:
        kind, _, idx = name.partition(":")
    else:
        kind, idx = name, "0"
    kind = {"gpu": "tpu", "xpu": "tpu", "cuda": "tpu"}.get(kind, kind)
    if kind == "cpu":
        _current_place = CPUPlace()
    elif kind == "tpu":
        _current_place = TPUPlace(int(idx))
    else:
        _current_place = CustomPlace(kind, int(idx))
    return _current_place


def get_device() -> str:
    p = _current_place or _default_place()
    if p.is_cpu_place():
        return "cpu"
    return f"{p.device_type}:{p.device_id}"


def _default_place() -> Place:
    devs = _accelerator_devices()
    if devs and devs[0].platform != "cpu":
        return TPUPlace(0)
    return CPUPlace()


def current_place() -> Place:
    return _current_place or _default_place()


def device_for_place(place: Place | None = None):
    """Resolve a Place to a concrete jax Device (or None = jax default)."""
    place = place or current_place()
    try:
        devs = jax.devices()
    except RuntimeError:
        return None
    if place.is_cpu_place():
        cpus = [d for d in devs if d.platform == "cpu"]
        if not cpus:
            try:
                cpus = jax.devices("cpu")
            except RuntimeError:
                return None
        return cpus[0] if cpus else None
    accel = [d for d in devs if d.platform != "cpu"] or devs
    idx = min(place.device_id, len(accel) - 1)
    return accel[idx]


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str) -> bool:
    return device_type == "tpu"
