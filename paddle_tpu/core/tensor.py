"""The Tensor: paddle's imperative tensor on an immutable jax.Array.

The reference's DenseTensor is buffer+meta (reference:
paddle/phi/core/dense_tensor.h — unverified, SURVEY.md §0) with true
in-place mutation; here "mutation" rebinds the wrapped immutable
``jax.Array`` (functionalization), which preserves paddle semantics for
every op while staying XLA-friendly. Tensor is registered as a jax pytree
node, so jitted functions can take and return Tensors directly.

Most op methods (``__add__``, ``.sum`` …) are monkey-patched onto this
class by ``paddle_tpu.tensor`` — the same layering the reference uses
(python/paddle/tensor/__init__.py patches methods onto the C++ tensor).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import autograd
from .dtype import DType, to_jax_dtype, to_paddle_dtype, get_default_dtype
from .place import Place, current_place, device_for_place

__all__ = ["Tensor", "Parameter", "to_tensor"]


def _coerce_value(data, dtype=None, place=None):
    """data (array-like / Tensor / scalar) → jax.Array on the right device."""
    if isinstance(data, Tensor):
        data = data._value
    jdt = to_jax_dtype(dtype) if dtype is not None else None
    if isinstance(data, jax.Array):
        return data.astype(jdt) if jdt is not None and data.dtype != jdt else data
    arr = np.asarray(data)
    if jdt is None:
        # paddle default promotion: python floats → default dtype;
        # python ints → int64.
        if arr.dtype == np.float64:
            jdt = to_jax_dtype(get_default_dtype())
        else:
            jdt = arr.dtype
    arr = arr.astype(jdt, copy=False)
    if place is not None:
        # explicit placement commits the buffer to that device
        return jax.device_put(arr, device_for_place(place))
    # uncommitted: follows the computation (composes with mesh-sharded
    # operands instead of pinning to device 0)
    return jnp.asarray(arr)


class Tensor:
    """paddle.Tensor analog wrapping a jax.Array (or tracer)."""

    __slots__ = (
        "_value",
        "_stop_gradient",
        "_grad",
        "_slot",
        "_name",
        "_grad_hooks",
        "_retain_grad_flag",
        "persistable",
        "_trainable_override",
        "__weakref__",
        "__dict__",
    )

    def __init__(self, value, dtype=None, place=None, stop_gradient=True, name=None):
        self._value = _coerce_value(value, dtype, place)
        self._stop_gradient = bool(stop_gradient)
        self._grad = None
        self._slot = None
        self._name = name
        self._grad_hooks = []
        self._retain_grad_flag = False
        self.persistable = False
        self._trainable_override = None

    # -- meta ---------------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    ndimension = ndim

    @property
    def dtype(self) -> DType:
        return to_paddle_dtype(self._value.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    def numel(self):
        return Tensor(jnp.asarray(self.size, dtype=jnp.int32))

    def dim(self):
        return self.ndim

    @property
    def place(self) -> Place:
        return current_place()

    @property
    def name(self):
        return self._name or f"tensor_{id(self):x}"

    @name.setter
    def name(self, v):
        self._name = v

    @property
    def stop_gradient(self):
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._stop_gradient = bool(v)

    @property
    def trainable(self):
        """Tracks stop_gradient (paddle semantics: flipping
        stop_gradient later must change what optimizers update) unless
        explicitly overridden via the setter (frozen Parameters)."""
        if self._trainable_override is not None:
            return self._trainable_override
        return not self._stop_gradient

    @trainable.setter
    def trainable(self, v):
        self._trainable_override = bool(v)

    @property
    def is_tensor(self):
        return True

    @property
    def T(self):
        from ..tensor.linalg import t

        return t(self)

    @property
    def mT(self):
        from ..tensor import manipulation as _m

        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return _m.transpose(self, perm)

    # -- grad ---------------------------------------------------------------
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g if (g is None or isinstance(g, Tensor)) else Tensor(g)

    def _ensure_slot(self):
        if self._slot is None:
            self._slot = autograd.GradSlot(owner=self)
        return self._slot

    def is_leaf(self) -> bool:
        return self._slot is None or self._slot.node is None

    @property
    def grad_fn(self):
        return self._slot.node if self._slot is not None else None

    def _set_grad_accum(self, g_value):
        if self._grad is None:
            self._grad = Tensor(g_value, stop_gradient=True)
        else:
            self._grad = Tensor(self._grad._value + g_value, stop_gradient=True)

    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def zero_grad(self):
        if self._grad is not None:
            self._grad = Tensor(jnp.zeros_like(self._grad._value))

    def register_hook(self, hook):
        self._grad_hooks.append(hook)

        class _Handle:
            def remove(h_self):
                try:
                    self._grad_hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def retain_grads(self):
        self._retain_grad_flag = True

    def detach(self):
        t = Tensor(self._value, stop_gradient=True)
        t._name = self._name
        return t

    def detach_(self):
        self._slot = None
        self._stop_gradient = True
        return self

    # -- conversion ---------------------------------------------------------
    def numpy(self):
        return np.asarray(jax.device_get(self._value))

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dtype):
        from .dispatch import apply

        return apply(
            lambda x: x.astype(to_jax_dtype(dtype)), self, op_name="cast"
        )

    def cast(self, dtype):
        return self.astype(dtype)

    def clone(self):
        from .dispatch import apply

        return apply(lambda x: x + 0 if jnp.issubdtype(x.dtype, jnp.inexact) else jnp.array(x), self, op_name="clone")

    def to(self, *args, **kwargs):
        """paddle Tensor.to(device|dtype|tensor)."""
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str, Place)):
                if isinstance(a, str) and a in DType._registry:
                    out = out.astype(a)
                else:
                    pass  # single logical device space; placement is a no-op
            elif isinstance(a, DType):
                out = out.astype(a)
            elif isinstance(a, Tensor):
                out = out.astype(a.dtype)
        return out

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -- mutation (functional rebind) ---------------------------------------
    def copy_(self, other, blocking=True):
        other = other if isinstance(other, Tensor) else Tensor(other)
        self._value = other._value.astype(self._value.dtype)
        return self

    def set_value(self, value):
        v = _coerce_value(value, dtype=self.dtype)
        if tuple(v.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {v.shape} vs {self._value.shape}"
            )
        # keep the existing distribution (a sharded param stays sharded)
        old_sharding = getattr(self._value, "sharding", None)
        if old_sharding is not None and getattr(v, "sharding", None) != old_sharding:
            try:
                v = jax.device_put(v, old_sharding)
            except Exception as e:
                raise ValueError(
                    f"set_value could not restore the tensor's sharding "
                    f"{old_sharding}: {e}"
                ) from e
        self._value = v
        return self

    get_tensor = lambda self: self  # LoDTensor compat

    def _rebind(self, new_tensor):
        """Adopt another Tensor's value+version (in-place op epilogue).

        Any previously recorded node keeps referencing this tensor's OLD
        GradSlot — the old version stays a valid graph vertex while the
        Python object moves on to the new version (see autograd.GradSlot).
        """
        import weakref as _wr

        self._value = new_tensor._value
        slot = new_tensor._slot
        if slot is not None:
            slot.owner_ref = _wr.ref(self)
            self._stop_gradient = new_tensor._stop_gradient
        self._slot = slot
        return self

    # -- protocol ------------------------------------------------------------
    def __jax_array__(self):
        return self._value

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        from ..jit import _current_guard_ctx

        ctx = _current_guard_ctx()
        if ctx is not None:
            # SOT-lite: to_static specializes on the recorded value (or
            # graph-breaks to learn it) instead of failing. EVERY Tensor
            # bool routes through the context in both modes — concrete
            # tensors too — so the eager-recorded guard tuple and the
            # traced predicate list stay index-aligned. ``self`` lets
            # concrete (closed-over) guards be re-checked host-side.
            return ctx.on_bool(self._value, owner=self)
        if isinstance(self._value, jax.core.Tracer):
            raise TypeError(
                "bool() on a traced Tensor inside jit/to_static: Python "
                "control flow would be baked at trace time. Use "
                "paddle.static.nn.cond / while_loop / switch_case (XLA "
                "structured control flow) or paddle.where instead."
            )
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.numpy().item(), spec)
        return format(str(self), spec)

    def __repr__(self):
        try:
            val = np.asarray(self._value)
            body = np.array2string(val, precision=8, separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}, stop_gradient={self._stop_gradient},\n"
            f"       {body})"
        )

    def __hash__(self):
        return id(self)

    # math/compare dunders and op methods are patched by paddle_tpu.tensor


class Parameter(Tensor):
    """Trainable tensor (reference: paddle Parameter / EagerParamBase)."""

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        # trainable tracks stop_gradient (no override): freezing a param
        # later via p.stop_gradient = True must stop optimizer updates

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


# -- pytree registration -----------------------------------------------------
def _tensor_flatten(t: Tensor):
    return (t._value,), (type(t), t._stop_gradient)


def _tensor_unflatten(aux, children):
    cls, stop_gradient = aux
    obj = Tensor.__new__(cls)
    obj._value = children[0]
    obj._stop_gradient = stop_gradient
    obj._grad = None
    obj._slot = None
    obj._name = None
    obj._grad_hooks = []
    obj._retain_grad_flag = False
    obj.persistable = False
    obj._trainable_override = None  # trainable keeps tracking stop_gradient
    return obj


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
jax.tree_util.register_pytree_node(Parameter, _tensor_flatten, _tensor_unflatten)
