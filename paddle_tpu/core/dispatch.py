"""Op dispatch: the Phi-analog single seam every op goes through.

The reference dispatches (op, backend, layout, dtype) → kernel via
``phi::KernelFactory`` (reference: paddle/phi/core/kernel_factory.cc —
unverified, SURVEY.md §0). Here the "kernel" is always a pure JAX function
and the dispatcher's job is autograd recording: run the function under
``jax.vjp`` when any input needs grad, wrap outputs as Tensors, and attach
one tape Node. Works on concrete arrays and on tracers (inside jit) alike.
"""
from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp

from . import autograd
from .flags import get_flags

__all__ = ["apply", "unwrap", "wrap_single", "OP_REGISTRY", "SEAM_OPS",
           "register_op"]

# op name → python callable (introspection / paddle "kernel registry" analog)
OP_REGISTRY: dict[str, object] = {}
# dispatch-seam op names observed at runtime (AMP's lists key on these).
# A name-only SET: storing apply()'s per-call closures would pin their
# captured arrays for the process lifetime
SEAM_OPS: set[str] = set()

_amp_cache = None


def _amp():
    """Lazily bind the amp module once (avoids an import cycle at boot)."""
    global _amp_cache
    if _amp_cache is None:
        try:
            from ..amp import amp_state, cast_inputs_for_op

            _amp_cache = (amp_state, cast_inputs_for_op)
        except ImportError:
            _amp_cache = False
    return _amp_cache or None


def register_op(name: str, fn):
    OP_REGISTRY[name] = fn
    return fn


def populate_op_registry():
    """Fill OP_REGISTRY with the framework's public op surface — the
    paddle "kernel registry" analog (reference: PD_REGISTER_KERNEL /
    phi::KernelFactory, SURVEY.md §2.1 — unverified). Registered:

    - every public callable on ``paddle.*`` (tensor/creation/math/...)
    - ``paddle.nn.functional.*`` under ``functional.<name>``
    - namespace APIs (linalg/fft/signal/sparse/geometric) under
      ``<ns>.<name>``

    Dispatch-seam op names (the strings ``apply(op_name=...)`` uses, which
    AMP's white/black lists key on) are additionally recorded at first
    execution by ``apply`` itself.
    """
    import inspect
    import paddle_tpu as _p

    def take(ns, prefix=""):
        for name in dir(ns):
            if name.startswith("_"):
                continue
            fn = getattr(ns, name, None)
            if inspect.isfunction(fn) or inspect.isbuiltin(fn):
                OP_REGISTRY.setdefault(prefix + name, fn)

    take(_p)
    take(_p.nn.functional, "functional.")
    for ns_name in ("linalg", "fft", "signal", "sparse", "geometric",
                    "incubate"):
        ns = getattr(_p, ns_name, None)
        if ns is not None:
            take(ns, ns_name + ".")
    return len(OP_REGISTRY)


def unwrap(x):
    """Tensor → jax value; everything else passes through."""
    from .tensor import Tensor

    if isinstance(x, Tensor):
        return x._value
    return x


def wrap_single(value, stop_gradient=True):
    from .tensor import Tensor

    return Tensor(value, stop_gradient=stop_gradient)


def _nan_report(name, bad):
    if bad:
        raise FloatingPointError(
            f"FLAGS_check_nan_inf: op '{name}' produced NaN/Inf"
        )


def _check_nan_inf(name, flat_vals):
    for v in flat_vals:
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.inexact):
            if isinstance(v, jax.core.Tracer):
                # jitted path: a host callback carries the check into the
                # compiled program (debug-flag overhead is acceptable —
                # the reference's check_nan_inf pass also syncs). The
                # callback's raise aborts the computation: it surfaces as
                # JaxRuntimeError("CpuCallback error ... FloatingPointError
                # ... NaN/Inf") at dispatch or first sync — verified on
                # jax 0.9 by tests/test_distributed.py::
                # test_nan_check_fires_inside_jit
                jax.debug.callback(
                    _nan_report, name, jnp.any(~jnp.isfinite(v))
                )
            elif bool(jnp.any(~jnp.isfinite(v))):
                raise FloatingPointError(
                    f"FLAGS_check_nan_inf: op '{name}' produced NaN/Inf"
                )


def apply(fn, *args, op_name: str = "", **kwargs):
    """Run op ``fn(*args, **kwargs)`` with autograd recording.

    ``args`` may contain Tensors (differentiable when
    ``stop_gradient=False``), jax arrays, or python scalars; ``kwargs``
    must be static (non-Tensor). Output mirrors ``fn``'s structure with
    every array wrapped as a Tensor.
    """
    from .tensor import Tensor

    if op_name:
        SEAM_OPS.add(op_name)
    vals = [unwrap(a) for a in args]
    # AMP: cast inputs per white/black list before tracing the op.
    amp = _amp()
    if amp is not None and amp[0].enabled:
        vals = amp[1](op_name, vals)
    diff_idx = (
        [
            i
            for i, a in enumerate(args)
            if isinstance(a, Tensor)
            and not a.stop_gradient
            and jnp.issubdtype(jnp.asarray(a._value).dtype, jnp.inexact)
        ]
        if autograd.is_grad_enabled()
        else []
    )

    if not diff_idx:
        out = fn(*vals, **kwargs)
        flat, treedef = jax.tree_util.tree_flatten(out)
        if get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]:
            _check_nan_inf(op_name or getattr(fn, "__name__", "op"), flat)
        wrapped = [Tensor(v, stop_gradient=True) for v in flat]
        return jax.tree_util.tree_unflatten(treedef, wrapped)

    def closed(*diff_vals):
        v = list(vals)
        for i, dv in zip(diff_idx, diff_vals):
            v[i] = dv
        return fn(*v, **kwargs)

    primals = tuple(vals[i] for i in diff_idx)
    out, vjp_fn = jax.vjp(closed, *primals)
    flat, treedef = jax.tree_util.tree_flatten(out)
    if get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]:
        _check_nan_inf(op_name or getattr(fn, "__name__", "op"), flat)

    # Outputs with inexact dtype participate in grad; int outputs don't.
    wrapped = [
        Tensor(
            v,
            stop_gradient=not jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact),
        )
        for v in flat
    ]
    node = autograd.Node(
        vjp_fn,
        [args[i]._ensure_slot() for i in diff_idx],
        [],
        treedef,
        name=op_name or getattr(fn, "__name__", "op"),
        closed=closed,
        primals=primals,
    )
    for t in wrapped:
        slot = autograd.GradSlot(owner=t, node=node if not t.stop_gradient else None)
        if not t.stop_gradient:
            t._slot = slot
        node.outputs.append(
            (slot, tuple(jnp.shape(t._value)), jnp.asarray(t._value).dtype)
        )
    return jax.tree_util.tree_unflatten(treedef, wrapped)
