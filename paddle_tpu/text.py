"""paddle.text — sequence decoding utilities (reference:
python/paddle/text/viterbi_decode.py — unverified, SURVEY.md §0).

``viterbi_decode`` runs the max-product recursion as a ``lax.scan``
(TPU-friendly static shapes; lengths masked) and recovers paths by a
reverse scan over the argmax backpointers. Datasets from the reference's
paddle.text.datasets require downloads (zero-egress here) and are not
provided."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .nn.layer.layers import Layer
from .tensor._helpers import apply, ensure_tensor

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding.

    potentials (B, T, N) emission scores, transition_params (N, N) with
    transition[i, j] = score of i → j, lengths (B,). With
    ``include_bos_eos_tag`` the last two tags are treated as BOS/EOS
    (reference semantics). Returns (scores (B,), paths (B, T) int32)."""
    potentials = ensure_tensor(potentials)
    transition_params = ensure_tensor(transition_params)
    lengths = ensure_tensor(lengths)

    def fn(emit, trans, lens):
        b, t, n = emit.shape
        if include_bos_eos_tag:
            bos, eos = n - 2, n - 1
            init = emit[:, 0] + trans[bos][None, :]
        else:
            init = emit[:, 0]

        def step(carry, xs):
            alpha = carry  # (B, N) best score ending at each tag
            e_t, idx = xs
            # (B, N_prev, N_next)
            scores = alpha[:, :, None] + trans[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)  # (B, N)
            alpha_new = jnp.max(scores, axis=1) + e_t
            # sequences already past their length keep their alpha
            active = (idx < lens)[:, None]
            alpha_out = jnp.where(active, alpha_new, alpha)
            bp = jnp.where(active, best_prev,
                           jnp.arange(n)[None, :])
            return alpha_out, bp

        xs = (jnp.swapaxes(emit[:, 1:], 0, 1), jnp.arange(1, t))
        alpha, bps = jax.lax.scan(step, init, xs)  # bps (T-1, B, N)
        if include_bos_eos_tag:
            alpha = alpha + trans[:, eos][None, :]
        best_last = jnp.argmax(alpha, axis=-1)  # (B,)
        best_score = jnp.max(alpha, axis=-1)

        def back(carry, bp_idx):
            tag = carry  # (B,)
            bp, idx = bp_idx  # (B, N), scalar
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            # positions beyond length-1 keep the final tag
            prev = jnp.where(idx < lens, prev, tag)
            return prev, tag

        first, path_rev = jax.lax.scan(
            back, best_last, (bps[::-1], jnp.arange(t - 1, 0, -1)),
        )
        # final carry is the step-0 tag; path_rev holds steps t-1 .. 1
        paths = jnp.concatenate(
            [first[:, None], path_rev[::-1].T], axis=1
        )  # (B, T)
        return best_score, paths.astype(jnp.int32)

    return apply(fn, potentials, transition_params, lengths,
                 op_name="viterbi_decode")


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = ensure_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(
            potentials, self.transitions, lengths,
            self.include_bos_eos_tag,
        )


# -- datasets (reference: python/paddle/text/datasets/ — unverified,
# SURVEY.md §0). Zero-egress: loads from a local archive path. ----------
class Imdb:
    """IMDB sentiment dataset from a local aclImdb tar archive
    (paddle.text.datasets.Imdb parity: tokenized docs + 0/1 labels,
    word_idx built from the train split with a frequency cutoff).

    Args:
        data_file: path to ``aclImdb_v1.tar.gz`` (or a compatible tar
            containing ``aclImdb/<mode>/<pos|neg>/*.txt``).
        mode: "train" or "test".
        cutoff: minimum word frequency for the vocabulary.
    """

    def __init__(self, data_file=None, mode="train", cutoff=150):
        import re
        import tarfile
        from collections import Counter

        if data_file is None or not __import__("os").path.exists(data_file):
            raise RuntimeError(
                "Imdb needs a local aclImdb archive (zero-egress "
                "environment): pass data_file=/path/to/aclImdb_v1.tar.gz"
            )
        self.mode = mode
        pat = re.compile(r"aclImdb/%s/(pos|neg)/.*\.txt$" % mode)
        train_pat = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        tok = re.compile(r"[a-z]+")
        docs_raw, labels = [], []
        counter = Counter()
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                name = member.name
                is_cur = bool(pat.match(name))
                is_train = bool(train_pat.match(name))
                if not (is_cur or is_train):
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").lower()
                words = tok.findall(text)
                if is_train:
                    counter.update(words)
                if is_cur:
                    docs_raw.append(words)
                    labels.append(0 if "/pos/" in name else 1)
        vocab = sorted(
            (w for w, c in counter.items() if c >= cutoff),
            key=lambda w: (-counter[w], w),
        )
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [
            np.asarray([self.word_idx.get(w, unk) for w in ws], np.int64)
            for ws in docs_raw
        ]
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]


class _DatasetsNS:
    """paddle.text.datasets namespace object."""

    Imdb = Imdb


datasets = _DatasetsNS()
