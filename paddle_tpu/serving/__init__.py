"""paddle_tpu.serving — continuous-batching inference over the paged
KV pool (reference: the 2.6-era serving loop around AnalysisPredictor /
``Predictor.run`` and the blocked-cache predictor — SURVEY.md §0/§2.6/
§3.5).

:class:`ServingEngine` multiplexes many in-flight requests over one
shared :class:`~paddle_tpu.nlp.paged_cache.PagedKVCachePool` and one
single-dispatch jitted decode step; :mod:`.scheduler` holds the
admission queue, slot table, and block accounting. With a
``spec_draft`` model the decode quantum becomes the ON-DEVICE
speculative round of :mod:`.speculative` (draft-γ scan + one-forward
verify + in-graph acceptance, both paged pools donated).

The FRONT DOOR (:mod:`.frontend` + :mod:`.policy`, entry point
``paddle.inference.serve()``) is the serving *system* over that loop:
:class:`ServingFrontDoor` streams tokens per request
(:class:`TokenStream`, sync or ``async for``), applies priority
classes (``BATCH < NORMAL < INTERACTIVE``) with pool-pressure
preemption (evict-and-recompute-on-resume, bit-exact continuation),
sheds load off the SLO burn-rate health report
(:class:`FrontDoorPolicy`), and drains gracefully.

PREFIX CACHING (``ServingEngine(prefix_cache=True)``, default off):
the pool's content-addressed index
(:mod:`paddle_tpu.nlp.paged_cache`) lets admissions alias full prompt
blocks another request already prefilled — copy-on-write isolates
writers, refcount-aware eviction reclaims cached blocks only at
refcount one, and the scheduler admits on NOVEL block demand. Streams
stay bit-identical to the unshared engine; prefill compute scales
with unique tokens.

RESILIENCE (:mod:`.faults` + :mod:`.resilience`, engine kwargs
``faults=`` / ``resilience=``): a deterministic seeded
:class:`FaultInjector` at the host boundaries (default disarmed —
byte-identical goldens), a p99-calibrated :class:`QuantumWatchdog`
with exponential-backoff retry, batch-bisect poison quarantine
(``finish_reason="error"``, everyone else keeps serving), degradation
ladders (spec auto-disable to the plain quantum, prefix-subtree
quarantine on content-verify mismatch, pool accounting rebuild from
live block tables), and crash recovery via ``engine.snapshot()`` /
``ServingEngine.restore()`` (recompute-on-resume, bit-exact greedy
continuation) — also exposed on the front door.

CLUSTER TIER (:mod:`.cluster`): :class:`ClusterRouter` fronts N
replicas with prefix-cache affinity (the public
:func:`~paddle_tpu.nlp.paged_cache.prompt_prefix_key` on a
consistent-hash ring), health-weighted balancing off each replica's
serializable load report (WARN demoted, CRITICAL skipped), and
prefill/decode role disaggregation with recompute-on-resume hand-off;
:class:`ClusterFrontDoor` keeps the exact :class:`TokenStream` API
plus cluster-wide drain, shed coordination, and fleet
snapshot/restore. Streams stay bit-identical to a single-replica run.

The compiled programs are pinned by the ``serving_decode_step`` /
``speculative_verify_step`` / ``serving_frontdoor_step`` /
``serving_prefix_step`` analysis Budgets (zero involuntary remat,
zero host callbacks, KV pools donated). Benched by
``scripts/bench_serving.py`` (ragged Poisson arrivals, speculative
serving vs the plain quantum, the ``serving_overload`` shed/no-shed
burst rows, and the ``shared_prefix`` cached/unshared arms).
"""
from .scheduler import Request, Scheduler, SchedulerConfig
from .engine import ServingEngine
from .speculative import make_spec_round
from .policy import (
    BATCH, INTERACTIVE, NORMAL, FrontDoorPolicy, choose_victim,
    no_shed_policy,
)
from .frontend import ServingFrontDoor, TokenStream
from .faults import FaultInjector, FaultSpec, InjectedFault
from .resilience import QuantumWatchdog, ResiliencePolicy
from .cluster import ClusterFrontDoor, ClusterReplica, ClusterRouter
from ..nlp.paged_cache import prompt_prefix_key

__all__ = ["Request", "Scheduler", "SchedulerConfig", "ServingEngine",
           "make_spec_round",
           "BATCH", "NORMAL", "INTERACTIVE", "FrontDoorPolicy",
           "choose_victim", "no_shed_policy",
           "ServingFrontDoor", "TokenStream",
           "FaultInjector", "FaultSpec", "InjectedFault",
           "QuantumWatchdog", "ResiliencePolicy",
           "ClusterReplica", "ClusterRouter", "ClusterFrontDoor",
           "prompt_prefix_key"]
