"""paddle_tpu.serving — continuous-batching inference over the paged
KV pool (reference: the 2.6-era serving loop around AnalysisPredictor /
``Predictor.run`` and the blocked-cache predictor — SURVEY.md §0/§2.6/
§3.5).

:class:`ServingEngine` multiplexes many in-flight requests over one
shared :class:`~paddle_tpu.nlp.paged_cache.PagedKVCachePool` and one
single-dispatch jitted decode step; :mod:`.scheduler` holds the
admission queue, slot table, and block accounting. With a
``spec_draft`` model the decode quantum becomes the ON-DEVICE
speculative round of :mod:`.speculative` (draft-γ scan + one-forward
verify + in-graph acceptance, both paged pools donated). The compiled
programs are pinned by the ``serving_decode_step`` /
``speculative_verify_step`` analysis Budgets (zero involuntary remat,
zero host callbacks, KV pools donated). Benched by
``scripts/bench_serving.py`` (ragged Poisson arrivals + speculative
serving vs the plain quantum).
"""
from .scheduler import Request, Scheduler, SchedulerConfig
from .engine import ServingEngine
from .speculative import make_spec_round

__all__ = ["Request", "Scheduler", "SchedulerConfig", "ServingEngine",
           "make_spec_round"]
