"""On-device speculative decoding round for the serving engine
(reference: the speculative-decoding serving mode of the reference NLP
stack — unverified, SURVEY.md §0; algorithm: speculative sampling à la
Leviathan et al. / Chen et al.).

PR 2's bench recorded the host-driven ``speculative_greedy_search``
losing ~1000x to the fused on-device loop (BENCH_NOTES "Speculative
decode perf"): per proposal round it paid γ draft dispatches, one
verify dispatch, and a host sync. Here the ENTIRE round is one jitted
program batched over the serving slot dimension:

- **draft phase**: a ``lax.scan`` of γ+1 single-token draft steps over
  the draft's own paged pool (``engine.paged_decode_math`` — the same
  step definition the plain quantum scans). Step j consumes token j-1's
  output, so the extra step γ exists purely to write proposal γ-1's KV
  for the full-accept path (the host engine's PR-1 stale-KV fix, now
  in-graph and unconditional: for rejecting slots that write lands
  beyond the valid length and is overwritten next round).
- **verify phase**: ONE target forward over the γ+1-token chunk
  ``[last_tok, p_0..p_{γ-1}]`` per slot (``paged_chunk_math``) — every
  position's logits in a single dispatch, KV written at
  ``seq_lens + j``.
- **acceptance in-graph**: the greedy arm accepts the longest prefix
  matching the target argmax and emits the target's own choice at the
  first mismatch, so the emitted stream IS the target's greedy stream
  (exact by construction). The sampling arm is rejection sampling:
  accept p_j with probability min(1, p(x)/q(x)) (p, q the FILTERED
  target/draft distributions), resample the first rejection from
  norm(max(p-q, 0)), bonus-sample position γ from the target — exact
  in distribution for ``decode_strategy="sampling"``. Token draws use
  the same ``fold_in(key, n_emitted)`` stream as the plain engine
  (acceptance/resample draws ride separate fold_in tags), so a
  draft==target sampling engine reproduces the plain sampling engine
  bit-for-bit on fixed seeds.
- **roll forward/back by length mask**: both pools advance
  ``seq_lens`` by the emitted count only; rejected proposals' KV slots
  simply fall beyond the new length and are overwritten by the next
  round's writes. eos/max-new retirement masks compose with the
  variable per-round yield exactly like the plain quantum's.

The engine jits this with the draft AND target pool buffers — plus
their int8 scale pools, empty pytrees on a float engine — donated
(``donate_argnums=(0, ..., 7)``); the compiled program is pinned by
the ``speculative_verify_step`` analysis budget (0 involuntary remat,
0 host syncs, 0 collectives, bf16 stays bf16, both pools donated).

TENSOR PARALLELISM: the round needs no code of its own — it is built
from the SAME ``paged_decode_math`` / ``paged_chunk_math`` the plain
quantum scans, whose KV writes re-pin the kv-head sharding under an
installed mesh (engine.py ``_pin_kv``). When the engine runs ``tp>1``
both models' params are mesh-sharded at build, BOTH paged pools carry
the kv-head split, and the whole draft+verify round stays one dispatch
whose collectives live in-graph — the ``serving_tp_step`` recipe's
census caps and the tp2 parity tests pin that shape.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import autograd
from ..jit import functional_call
from ..nlp.generation import _filter_logits
from .engine import paged_decode_math, paged_chunk_math

__all__ = ["make_spec_round"]

# fold_in stream tags: acceptance-test uniforms and residual-resample
# draws must be independent of the token-proposal stream (which reuses
# the plain engine's fold_in(key, n_emitted) discipline for parity)
_ACC_TAG = 0x5ACC
_RES_TAG = 0x5E5A


def _stream_keys(keys, base, tag, n):
    """(S, n) raw keys: fold the per-slot key with ``tag`` then with
    the absolute emission index base+j — deterministic per (slot,
    position), independent across tags."""
    def per_slot(key, b):
        tagged = jax.random.fold_in(key, tag)
        return jax.vmap(lambda j: jax.random.fold_in(tagged, b + j))(
            jnp.arange(n))

    return jax.vmap(per_slot)(keys, base)


def make_spec_round(engine):
    """Build the speculative round for ``engine`` (a
    :class:`~paddle_tpu.serving.ServingEngine` with ``spec_draft``):
    returns the pure function the engine jits with both pools donated.

    State contract (mirrors the plain quantum): ``seq_lens`` counts
    tokens IN both caches (identical histories by construction),
    ``last_tok`` is the newest emitted token not yet cached. Returns
    ``(t_kc, t_vc, t_ks, t_vs, d_kc, d_vc, d_ks, d_vs, seq_lens,
    last_tok, n_gen, done, stream, emitted, accepted)`` where
    ``stream`` is the (S, γ+1) emission matrix, ``emitted`` the
    per-slot valid prefix length (yield after eos/max-new caps), and
    ``accepted`` the raw per-slot acceptance count for the serving
    stats. The ``*_ks``/``*_vs`` pytrees are the int8 pools' per-row
    scale pools; on a float engine they are EMPTY tuples (zero avals —
    the compiled round and its golden are byte-identical)."""
    target = engine.model
    draft = engine.spec_draft
    gamma = int(engine.spec_gamma)
    greedy = engine.decode_strategy == "greedy"
    top_k, top_p, temp = engine.top_k, engine.top_p, engine.temperature
    has_eos = engine.eos_token_id is not None
    eos = -1 if engine.eos_token_id is None else int(engine.eos_token_id)
    t_scratch = engine._scratch_block
    d_scratch = engine._d_scratch_block

    def spec_round(t_kc, t_vc, t_ks, t_vs, d_kc, d_vc, d_ks, d_vs,
                   t_pv, d_pv, t_tables, d_tables, seq_lens, last_tok,
                   n_gen, done, max_new, keys):
        live = ~done
        s_ = last_tok.shape[0]

        # -- draft: γ+1 single-token steps under one lax.scan ---------
        def draft_body(carry, j):
            kcs, vcs, kss, vss, cur = carry
            with autograd.no_grad():
                def fwd(tok_t):
                    return paged_decode_math(
                        draft, d_scratch, tok_t, seq_lens + j,
                        d_tables, kcs, vcs, live, ks=kss, vs=vss)

                (logits, kcs2, vcs2, kss2, vss2), _ = functional_call(
                    draft, fwd,
                    [Tensor(cur[:, None], stop_gradient=True)], {},
                    d_pv, [])
            if greedy:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                q = jnp.zeros((s_, 1), jnp.float32)  # unused, DCE'd
            else:
                filt = _filter_logits(logits, top_k, top_p, temp)
                step_keys = jax.vmap(jax.random.fold_in)(keys,
                                                         n_gen + j)
                nxt = jax.vmap(jax.random.categorical)(
                    step_keys, filt).astype(jnp.int32)
                q = jax.nn.softmax(filt, axis=-1)
            return (kcs2, vcs2, kss2, vss2, nxt), (nxt, q)

        (d_kc, d_vc, d_ks, d_vs, _), (props, qs) = jax.lax.scan(
            draft_body,
            (d_kc, d_vc, tuple(d_ks), tuple(d_vs), last_tok),
            jnp.arange(gamma + 1))
        prop_sg = jnp.transpose(props[:gamma])           # (S, γ)
        chunk = jnp.concatenate([last_tok[:, None], prop_sg], axis=1)

        # -- verify: ONE target forward over all γ+1 positions --------
        with autograd.no_grad():
            def tfwd(ids_t):
                return paged_chunk_math(
                    target, t_scratch, ids_t, seq_lens, t_tables,
                    t_kc, t_vc, live, ks=t_ks, vs=t_vs)

            (t_logits, t_kc2, t_vc2, t_ks2, t_vs2), _ = functional_call(
                target, tfwd, [Tensor(chunk, stop_gradient=True)], {},
                t_pv, [])

        # -- acceptance prefix + bonus/resample, in-graph -------------
        pos = jnp.arange(gamma + 1)
        if greedy:
            # accepted proposals EQUAL the target argmax, so the
            # emission stream is the target's own choice at every
            # position — exactness by construction
            t_choice = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
            match = prop_sg == t_choice[:, :gamma]
            a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                        axis=1)
            stream = t_choice
        else:
            v = t_logits.shape[-1]
            filt_t = _filter_logits(
                t_logits.reshape(s_ * (gamma + 1), v), top_k, top_p,
                temp).reshape(s_, gamma + 1, v)
            p_probs = jax.nn.softmax(filt_t, axis=-1)
            q_probs = jnp.transpose(qs[:gamma], (1, 0, 2))
            p_at = jnp.take_along_axis(
                p_probs[:, :gamma], prop_sg[..., None], axis=-1)[..., 0]
            q_at = jnp.take_along_axis(
                q_probs, prop_sg[..., None], axis=-1)[..., 0]
            ratio = p_at / jnp.maximum(q_at, 1e-30)
            acc_keys = _stream_keys(keys, n_gen, _ACC_TAG, gamma)
            u = jax.vmap(jax.vmap(jax.random.uniform))(acc_keys)
            accept = u < jnp.minimum(ratio, 1.0)
            a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                        axis=1)
            # first rejection resamples the residual max(p-q, 0); a
            # numerically-empty residual (p==q) can only pair with an
            # always-accept ratio, but guard with the target dist
            resid = jnp.maximum(p_probs[:, :gamma] - q_probs, 0.0)
            rsum = resid.sum(-1, keepdims=True)
            corr_logits = jnp.where(rsum > 0.0, jnp.log(resid),
                                    filt_t[:, :gamma])
            res_keys = _stream_keys(keys, n_gen, _RES_TAG, gamma)
            res = jax.vmap(jax.vmap(jax.random.categorical))(
                res_keys, corr_logits).astype(jnp.int32)
            # full accept: bonus-sample position γ from the target on
            # the TOKEN stream key — a draft==target engine therefore
            # replays the plain sampling engine exactly
            bonus_keys = jax.vmap(jax.random.fold_in)(keys,
                                                      n_gen + gamma)
            bonus = jax.vmap(jax.random.categorical)(
                bonus_keys, filt_t[:, gamma]).astype(jnp.int32)
            corr = jnp.concatenate([res, bonus[:, None]], axis=1)
            stream = jnp.where(
                pos[None, :] < a[:, None],
                jnp.concatenate([prop_sg, prop_sg[:, :1]], axis=1),
                corr)

        # -- yield caps (max_new, eos) + state roll ------------------
        remaining = jnp.maximum(max_new - n_gen, 0)
        e = jnp.minimum(a + 1, remaining)
        if has_eos:
            hit = (stream == eos) & (pos[None, :] < e[:, None])
            any_hit = jnp.any(hit, axis=1)
            first = jnp.argmax(hit, axis=1)
            e = jnp.where(any_hit, first + 1, e)
        e = jnp.where(live, e, 0).astype(jnp.int32)
        n_gen2 = n_gen + e
        done2 = done | (n_gen2 >= max_new)
        if has_eos:
            done2 = done2 | (live & any_hit)
        # roll both caches forward by the emitted count only — stale
        # proposal slots beyond seq_lens2 ARE the rollback (length
        # masks hide them; next round's writes reuse them)
        seq_lens2 = seq_lens + e
        idx = jnp.maximum(e - 1, 0)
        new_last = jnp.take_along_axis(stream, idx[:, None],
                                       axis=1)[:, 0]
        last_tok2 = jnp.where(e > 0, new_last, last_tok) \
            .astype(jnp.int32)
        acc = jnp.where(live, a, 0).astype(jnp.int32)
        return (t_kc2, t_vc2, t_ks2, t_vs2, d_kc, d_vc, d_ks, d_vs,
                seq_lens2, last_tok2, n_gen2, done2, stream, e, acc)

    return spec_round
