"""Deterministic, seeded fault injection for the serving engine
(reference: the restart/fault semantics the Fleet elastic-launch tier
assumes — PAPER.md north-star — and ROADMAP item 5's adversarial soak:
"window x spec x preempt x COW interleavings as a randomized soak that
replays any failure from its seed + flight journals").

The injector threads through the engine's EXISTING host boundaries —
quantum dispatch (``before_dispatch``), pool ``_alloc_block``
(``on_alloc`` via ``pool.fault_hook``), and the per-step KV corruption
sweep (``maybe_corrupt``) — and never touches the compiled graphs:
every injected fault fires on the host BEFORE the device dispatch it
targets, so a retried quantum re-runs against un-donated, un-mutated
buffers and the ``max_host_callbacks=0`` budgets of every serving
recipe are untouched. A default-constructed injector (empty plan) is
**disarmed**: every hook is a constant-time no-op and all compiled
goldens stay byte-identical (the analysis recipes build their engines
with a disarmed injector to pin exactly that).

Determinism contract: same ``seed`` + same ``plan`` + same call
sequence -> the same faults fire at the same call indices and the
``journal`` lists are identical. The chaos soak replays any failure
from its seed plus the engine's flight journal.
"""
from __future__ import annotations

import random
import time

import numpy as np

__all__ = ["InjectedFault", "FaultSpec", "FaultInjector",
           "FAULT_SITES", "FAULT_KINDS"]

#: host boundaries the injector can target: the three quantum kinds
#: (matching ``obs.on_quantum``'s kind labels), the pool allocator,
#: cached-KV corruption, and the prefix-verify walk.
FAULT_SITES = ("decode", "spec_round", "mixed", "alloc", "kv", "prefix")

#: what fires at a matched site: ``raise`` (an :class:`InjectedFault`
#: before dispatch), ``slow`` (sleep ``sleep_s`` — watchdog fodder),
#: ``alloc_fail`` (the pool raises as if exhausted), ``bit_flip``
#: (corrupt one element of a cached-only KV block), ``poison`` (mark a
#: live request so every dispatch containing it raises — the batch
#: bisect isolates it).
FAULT_KINDS = ("raise", "slow", "alloc_fail", "bit_flip", "poison")


class InjectedFault(RuntimeError):
    """A fault the injector raised on purpose. The engine retries ONLY
    this type (real exceptions keep fail-stop semantics); ``site`` /
    ``kind`` say where it fired, ``poison`` carries the poisoned
    req_id when the fault is a poison trip."""

    def __init__(self, site, kind, detail=None, poison=None):
        self.site = site
        self.kind = kind
        self.poison = poison
        msg = f"injected {kind} at {site}"
        if poison is not None:
            msg += f" (poison {poison})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class FaultSpec:
    """One declarative fault: fire ``kind`` at ``site`` with
    probability ``p`` per eligible call, at most ``times`` times
    (None = unbounded). ``sleep_s`` sizes a ``slow`` fault's stall;
    ``detail`` rides into the raised message."""

    def __init__(self, site, kind, p=1.0, times=None, sleep_s=0.05,
                 detail=None):
        if site not in FAULT_SITES:
            raise ValueError(f"unknown fault site {site!r}; "
                             f"expected one of {FAULT_SITES}")
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; "
                             f"expected one of {FAULT_KINDS}")
        self.site = site
        self.kind = kind
        self.p = float(p)
        self.times = None if times is None else int(times)
        self.sleep_s = float(sleep_s)
        self.detail = detail
        self.fired = 0

    def exhausted(self):
        return self.times is not None and self.fired >= self.times

    def __repr__(self):
        return (f"FaultSpec({self.site!r}, {self.kind!r}, p={self.p}, "
                f"times={self.times}, fired={self.fired})")


class FaultInjector:
    """Seeded declarative fault injection at the engine's host
    boundaries.

    Args:
        plan: iterable of :class:`FaultSpec` (or ``(site, kind)`` /
            ``(site, kind, p)`` tuples). Empty -> disarmed no-op.
        seed: seeds the private ``random.Random`` that draws every
            per-call fire/skip decision — same seed + plan + call
            sequence replays the same faults.
        sleep: injectable stall fn for ``slow`` faults (tests pass a
            stub; default ``time.sleep``).
    """

    def __init__(self, plan=(), seed=0, sleep=time.sleep):
        self.plan = [s if isinstance(s, FaultSpec) else FaultSpec(*s)
                     for s in plan]
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._sleep = sleep
        self.injected_total = 0
        self.journal = []          # replayable record of every fire
        self._poisoned = set()     # req_ids whose dispatches raise
        self._calls = 0            # monotone call index (journal key)

    # -- arming state ------------------------------------------------------
    @property
    def armed(self):
        """True while any spec can still fire or a poison is pending —
        a disarmed injector's hooks are constant-time no-ops."""
        return (bool(self._poisoned)
                or any(not s.exhausted() for s in self.plan))

    def poison(self, req_id):
        """Mark ``req_id`` as poison: every dispatch whose active rows
        include it raises until the engine's bisect quarantine finishes
        it with ``finish_reason="error"`` and calls :meth:`cure`."""
        self._poisoned.add(str(req_id))

    def cure(self, req_id):
        self._poisoned.discard(str(req_id))

    @property
    def poisoned(self):
        return frozenset(self._poisoned)

    # -- plan matching -----------------------------------------------------
    def _fire(self, spec, site, **extra):
        spec.fired += 1
        self.injected_total += 1
        self.journal.append({"call": self._calls, "site": site,
                             "kind": spec.kind, **extra})

    def _match(self, site, kinds):
        """First live spec for ``site`` with a kind in ``kinds`` whose
        coin flip lands — the rng is consulted for every candidate so
        the decision sequence is a pure function of seed + plan +
        call order."""
        for spec in self.plan:
            if spec.site != site or spec.kind not in kinds:
                continue
            if spec.exhausted():
                continue
            if self._rng.random() < spec.p:
                return spec
        return None

    # -- engine hooks ------------------------------------------------------
    def before_dispatch(self, site, active_req_ids=()):
        """Called by the engine immediately BEFORE a quantum dispatch
        (site in decode | spec_round | mixed) with the req_ids of the
        rows about to run. Raises :class:`InjectedFault` for a matched
        ``raise`` spec or a poisoned active row; stalls for a matched
        ``slow`` spec. Firing before dispatch keeps retries
        side-effect-free (no donated buffer has been consumed)."""
        if not (self.plan or self._poisoned):
            return
        self._calls += 1
        for rid in active_req_ids:
            if str(rid) in self._poisoned:
                spec = self._match(site, ("poison",))
                if spec is not None:
                    self._fire(spec, site, poison=str(rid))
                else:
                    self.injected_total += 1
                    self.journal.append(
                        {"call": self._calls, "site": site,
                         "kind": "poison", "poison": str(rid)})
                raise InjectedFault(site, "poison", poison=str(rid))
        spec = self._match(site, ("raise", "slow"))
        if spec is None:
            return
        if spec.kind == "slow":
            self._fire(spec, site, sleep_s=spec.sleep_s)
            self._sleep(spec.sleep_s)
            return
        self._fire(spec, site)
        raise InjectedFault(site, "raise", detail=spec.detail)

    def on_alloc(self, pool):
        """Bound to ``pool.fault_hook``: called inside
        ``_alloc_block`` before a block leaves the free list. A matched
        ``alloc_fail`` raises :class:`InjectedFault` — the pool's
        state is untouched (nothing was popped yet), so the engine can
        simply retry the step."""
        if not self.plan:
            return
        self._calls += 1
        spec = self._match("alloc", ("alloc_fail",))
        if spec is None:
            return
        self._fire(spec, "alloc")
        raise InjectedFault("alloc", "alloc_fail", detail=spec.detail)

    def maybe_corrupt(self, pool):
        """Called once per engine step: a matched ``kv``/``bit_flip``
        spec flips one bit of one element in a CACHED-ONLY block
        (refcount==1 and held solely by the prefix index) of layer 0's
        K pool — corruption that the chain-hash verify at the next
        ``attach_prefix`` must catch, without ever corrupting a live
        request's stream. No eligible block -> records a skip and does
        nothing. Returns the corrupted block id or None."""
        if not self.plan:
            return None
        self._calls += 1
        spec = self._match("kv", ("bit_flip",))
        if spec is None:
            return None
        held = set()
        for blocks in pool._tables.values():
            held.update(blocks)
        victims = sorted(b for b, e in pool._cached_blocks.items()
                         if pool._refcounts.get(b) == 1
                         and b not in held)
        if not victims:
            self.journal.append({"call": self._calls, "site": "kv",
                                 "kind": "bit_flip", "skipped": True})
            return None
        blk = victims[self._rng.randrange(len(victims))]
        kp = np.asarray(pool.k_pools[0]).copy()
        flat = kp.reshape(kp.shape[0], -1)
        j = self._rng.randrange(flat.shape[1])
        raw = flat[blk].view(np.uint16 if flat.dtype.itemsize == 2
                             else np.uint32)
        bit = self._rng.randrange(raw.dtype.itemsize * 8)
        raw[j] = raw[j] ^ np.asarray(1 << bit, raw.dtype)
        pool.k_pools[0] = pool._pin(kp)
        self._fire(spec, "kv", block=int(blk), elem=int(j),
                   bit=int(bit))
        return int(blk)

    # -- views -------------------------------------------------------------
    def stats(self):
        return {
            "seed": self.seed,
            "armed": self.armed,
            "injected_total": self.injected_total,
            "poisoned": sorted(self._poisoned),
            "plan": [repr(s) for s in self.plan],
            "journal_len": len(self.journal),
        }
