"""Front-door serving policy: priority classes, SLO-burn-rate load
shedding, queue backpressure, and preemption victim selection
(reference: the admission/scheduling tier around the reference's
deployed AnalysisPredictor / ``Predictor.run`` services — PAPER.md
§2.6/§3.5's serving story run as an *operated* system; the burn-rate
gate itself consumes the SRE-style health report of
:mod:`paddle_tpu.obs.slo`).

Everything here is pure host-side decision logic over plain numbers —
no jax, no engine state mutation. The MECHANISMS live elsewhere:
eviction in :meth:`~paddle_tpu.serving.scheduler.Scheduler.preempt` /
:meth:`~paddle_tpu.serving.engine.ServingEngine.preempt`, shedding
accounting in :meth:`~paddle_tpu.obs.serving.ServingObs.on_shed`, and
the pump that applies this policy in serving/frontend.py.

Priority classes are small ints ordered ``BATCH < NORMAL <
INTERACTIVE`` (higher admits first; strictly-higher may preempt
lower). The default shedding ladder follows the health state:

- ``ok`` — admit everything (subject to queue backpressure).
- ``warn`` — shed ``shed_on_warn`` classes (default: BATCH only).
- ``critical`` — shed ``shed_on_critical`` classes too (default:
  BATCH + NORMAL; INTERACTIVE is never shed by the stock policy — a
  front door that sheds its most latency-sensitive class has given
  up).

Queue backpressure is health-independent: with ``max_waiting`` set, a
submission that finds the waiting queue at/over the bound is shed
unless its class is at least ``backpressure_exempt`` (default
INTERACTIVE) — bounding queue-wait-driven TTFT before the burn rate
ever trips.
"""
from __future__ import annotations

from ..obs.slo import state_of

__all__ = ["BATCH", "NORMAL", "INTERACTIVE", "PRIORITY_NAMES",
           "FrontDoorPolicy", "choose_victim"]

BATCH, NORMAL, INTERACTIVE = 0, 1, 2
PRIORITY_NAMES = {BATCH: "batch", NORMAL: "normal",
                  INTERACTIVE: "interactive"}


def choose_victim(live_requests, below_priority):
    """Pick the preemption victim among live requests strictly below
    ``below_priority``: the LOWEST class first (cheap work yields to
    expensive), newest admission within a class (LIFO — the oldest
    in-flight request of a class is closest to finishing, so evicting
    the newest wastes the least progress and the least recompute).
    None when no live request may be evicted for this candidate."""
    victims = [r for r in live_requests
               if not r.finished and r.slot is not None
               and r.priority < below_priority]
    if not victims:
        return None
    return max(victims,
               key=lambda r: (-r.priority,
                              r.admit_time if r.admit_time is not None
                              else float("-inf")))


class FrontDoorPolicy:
    """The front door's admission/preemption knobs.

    Args:
        shed_on_warn: priority classes shed while health is ``warn``
            (both burn-rate windows hot at the warn gate).
        shed_on_critical: classes shed at ``critical`` — the warn set
            is implied (a class shed at warn is certainly shed at
            critical).
        max_waiting: queue-depth backpressure bound (None = unbounded);
            submissions finding ``len(waiting) >= max_waiting`` are
            shed with reason ``backpressure``.
        backpressure_exempt: minimum class exempt from backpressure
            (default INTERACTIVE).
        preempt: enable eviction of strictly-lower-priority victims
            when the highest-priority waiting request cannot admit.
        max_preemptions_per_pump: cap evictions per scheduler
            iteration (thrash bound; one victim usually frees both a
            slot and blocks).
        health_interval_s: minimum seconds between ``engine.health()``
            evaluations (the report is cached in between — a burst of
            submissions must not turn admission into a burn-rate
            benchmark).
    """

    def __init__(self, shed_on_warn=(BATCH,),
                 shed_on_critical=(BATCH, NORMAL), max_waiting=None,
                 backpressure_exempt=INTERACTIVE, preempt=True,
                 max_preemptions_per_pump=4, health_interval_s=0.05):
        self.shed_on_warn = frozenset(int(p) for p in shed_on_warn)
        self.shed_on_critical = (frozenset(int(p)
                                           for p in shed_on_critical)
                                 | self.shed_on_warn)
        self.max_waiting = (None if max_waiting is None
                            else int(max_waiting))
        self.backpressure_exempt = int(backpressure_exempt)
        self.preempt = bool(preempt)
        self.max_preemptions_per_pump = int(max_preemptions_per_pump)
        self.health_interval_s = float(health_interval_s)

    def admission(self, priority, health_state, waiting_depth):
        """(admit, reason): reason is None on admit, else the shed
        reason (``backpressure`` | ``slo_warn`` | ``slo_critical``)."""
        priority = int(priority)
        if (self.max_waiting is not None
                and waiting_depth >= self.max_waiting
                and priority < self.backpressure_exempt):
            return False, "backpressure"
        state = state_of(health_state)
        if state >= "critical" and priority in self.shed_on_critical:
            return False, "slo_critical"
        if state >= "warn" and priority in self.shed_on_warn:
            return False, "slo_warn"
        return True, None


def no_shed_policy(preempt=False):
    """The pass-through baseline (the overload bench's no-shed arm):
    never sheds, never backpressures; preemption off by default."""
    return FrontDoorPolicy(shed_on_warn=(), shed_on_critical=(),
                           max_waiting=None, preempt=preempt)


__all__.append("no_shed_policy")
