"""Watchdog + retry/quarantine policy for the serving engine
(reference: the restart semantics of ``paddle.distributed.launch`` /
Fleet elastic launch — PAPER.md north-star — brought down to the
single-replica tier: before a cluster can fail over between replicas,
one replica must survive, degrade, and recover deterministically).

Two pieces, both pure host-side policy (no compiled graph changes):

- :class:`QuantumWatchdog` — a per-quantum wall-clock deadline derived
  from the engine's OWN quantum-seconds distribution: deadline(kind) =
  p99(kind) x ``deadline_margin``, gated on ``min_samples``
  observations and floored at ``min_deadline_s``. It owns a PRIVATE
  :class:`~paddle_tpu.obs.registry.Histogram` (not the obs registry's)
  so it works under ``obs="off"`` and never double-counts the exported
  ``serving_quantum_seconds`` series. Dispatch is synchronous, so the
  watchdog is detection-only: an overrun trips AFTER the quantum
  returns, feeding the trips counter and the spec-disable degradation
  ladder rather than interrupting the dispatch.
- :class:`ResiliencePolicy` — the knobs: retry budget + exponential
  backoff for :class:`~paddle_tpu.serving.faults.InjectedFault`
  retries, the watchdog's margin/floor/min-samples, and the
  ``spec_fault_threshold`` at which repeated spec-round faults
  auto-disable speculative decoding (degrading to the plain quantum —
  same compiled executable, no new golden). ``sleep`` is injectable so
  tests assert backoff schedules without wall-clock waits.
"""
from __future__ import annotations

import time

from ..obs.registry import Histogram

__all__ = ["ResiliencePolicy", "QuantumWatchdog"]


class ResiliencePolicy:
    """Knobs for the engine's fault handling (``resilience=True``
    builds the stock policy).

    Args:
        max_retries: injected-fault retries per dispatch before the
            engine escalates (poison -> bisect quarantine; transient ->
            skip the step and let the next step retry naturally).
        backoff_base_s / backoff_mult: exponential backoff between
            retries — retry i sleeps ``base * mult**i``.
        deadline_margin: watchdog deadline = p99 x margin.
        min_deadline_s: floor under the p99-derived deadline (tiny CPU
            quanta would otherwise trip on scheduler jitter).
        min_samples: observations per quantum kind before the watchdog
            arms (no deadline until the histogram is warm).
        spec_fault_threshold: spec-round faults/trips before the
            engine one-way degrades to the plain quantum.
        sleep: injectable stall fn for the backoff (tests pass a stub).
    """

    def __init__(self, max_retries=3, backoff_base_s=0.01,
                 backoff_mult=2.0, deadline_margin=20.0,
                 min_deadline_s=0.25, min_samples=16,
                 spec_fault_threshold=3, sleep=time.sleep):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if spec_fault_threshold < 1:
            raise ValueError("spec_fault_threshold must be >= 1")
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_mult = float(backoff_mult)
        self.deadline_margin = float(deadline_margin)
        self.min_deadline_s = float(min_deadline_s)
        self.min_samples = int(min_samples)
        self.spec_fault_threshold = int(spec_fault_threshold)
        self.sleep = sleep

    def backoff_s(self, attempt):
        """Stall before retry ``attempt`` (0-based)."""
        return self.backoff_base_s * (self.backoff_mult ** attempt)


class QuantumWatchdog:
    """Wall-clock overrun detection per quantum kind, self-calibrated
    from the engine's own latency distribution."""

    def __init__(self, policy=None):
        self.policy = policy if policy is not None else ResiliencePolicy()
        # private histogram: independent of any obs registry so the
        # watchdog works under obs="off" and the exported
        # serving_quantum_seconds series is never double-counted
        self._hist = Histogram("watchdog_quantum_seconds")
        self.trips_total = 0
        self.trips = {}  # kind -> count

    def observe(self, kind, dt):
        self._hist.observe(float(dt), kind=str(kind))

    def deadline(self, kind):
        """Current deadline for ``kind`` in seconds, or None while the
        histogram is cold (fewer than ``min_samples`` observations)."""
        if self._hist.count(kind=str(kind)) < self.policy.min_samples:
            return None
        p99 = self._hist.quantile(0.99, kind=str(kind))
        if p99 is None:
            return None
        return max(p99 * self.policy.deadline_margin,
                   self.policy.min_deadline_s)

    def check(self, kind, elapsed):
        """Record ``elapsed`` then test it against the deadline that
        held BEFORE this observation; returns True on a trip."""
        limit = self.deadline(kind)
        self.observe(kind, elapsed)
        if limit is not None and elapsed > limit:
            self.trips_total += 1
            self.trips[kind] = self.trips.get(kind, 0) + 1
            return True
        return False

    def stats(self):
        return {"trips_total": self.trips_total,
                "trips": dict(self.trips)}
