"""Seeded chaos soak for the resilience tier (ROADMAP item 5's
adversarial interleaving soak: faults x speculative decoding x
preemption x copy-on-write prefix sharing, replayable from its seed).

:func:`run_soak` drives TWO engines over the SAME seeded workload:

- the CLEAN arm runs fault-free and produces the reference streams;
- the FAULTED arm runs the identical submissions under an armed
  :class:`~paddle_tpu.serving.FaultInjector` (transient raises, slow
  quanta, allocation failures, cached-KV bit flips, poisons) plus
  seeded mid-flight preemptions, with the resilience tier containing
  everything.

Greedy rows are batch-independent and recompute-on-resume is
bit-exact, so the soak's core invariant is sharp: every NON-POISONED
request in the faulted arm must match the clean arm byte-for-byte, no
matter which faults fired between its tokens. The other hard checks:
every request ends with a definite ``finish_reason``, and the pool
leaks nothing (blocks in use at drain == the engine scratch block +
the prefix index's cached blocks).

Any failure replays from ``seed`` alone — the injector's journal and
the engine's flight recorder carry the full interleaving. CLI wrapper:
``scripts/soak.py``; the tier-1 smoke and the 200-round slow soak live
in tests/test_resilience.py; ``python -m paddle_tpu.obs check`` runs a
bounded smoke as a CI gate.
"""
from __future__ import annotations

import numpy as np

from .engine import ServingEngine
from .faults import FaultInjector, FaultSpec
from .resilience import ResiliencePolicy

__all__ = ["soak_plan", "run_soak"]

# block-aligned tail lengths: ragged enough to exercise COW + chunked
# prefill, few enough distinct mixed-step shapes that the CPU soak's
# compile count stays bounded (every combo amortizes over the run)
_PROMPT_LENS = (4, 8)


def _no_sleep(_s):
    return None


def soak_plan(seed, rounds, vocab_size, spec=False):
    """The seeded workload + fault plan: a list of per-round
    submissions (round, req_id, prompt, max_new, poison) and the
    injector's :class:`FaultSpec` list. Pure function of the
    arguments — the replay contract."""
    rng = np.random.RandomState(seed)
    prefix = rng.randint(1, vocab_size, 8).astype(np.int32)
    subs = []
    i = 0
    for rnd in range(rounds):
        n_new = int(rng.random() < 0.7)
        for _ in range(n_new):
            tail_len = int(_PROMPT_LENS[rng.randint(len(_PROMPT_LENS))])
            tail = rng.randint(1, vocab_size, tail_len).astype(np.int32)
            shared = bool(rng.random() < 0.5)
            prompt = (np.concatenate([prefix, tail]) if shared
                      else tail)
            subs.append({
                "round": rnd,
                "req_id": f"soak-{i}",
                "prompt": prompt,
                "max_new": int(rng.randint(3, 9)),
                "poison": bool(rng.random() < 0.06),
            })
            i += 1
    plan = [
        FaultSpec("decode", "raise", p=0.05),
        FaultSpec("mixed", "raise", p=0.03),
        FaultSpec("alloc", "alloc_fail", p=0.03),
        FaultSpec("kv", "bit_flip", p=0.10),
        FaultSpec("decode", "slow", p=0.02, sleep_s=0.001),
    ]
    if spec:
        plan.append(FaultSpec("spec_round", "raise", p=0.05))
    return subs, plan


def _drain(engine, budget=10000):
    steps = 0
    while engine.step():
        steps += 1
        if steps > budget:
            raise RuntimeError("soak engine failed to drain")
    return steps


def _expected_residency(pool):
    # scratch block + whatever the prefix index still holds
    return 1 + int(getattr(pool, "cached_blocks", 0))


def run_soak(model, spec_draft=None, rounds=50, seed=0, num_slots=3,
             block_size=4, prefill_chunk=4, decode_quantum=3,
             prefix_cache=True):
    """Run the two-arm chaos soak; returns the report dict and raises
    ``AssertionError`` on any invariant breach. Same (model, kwargs,
    seed) -> same faults, same streams, same report."""
    vocab = int(model.config.vocab_size)
    subs, plan = soak_plan(seed, rounds, vocab,
                           spec=spec_draft is not None)
    kwargs = dict(num_slots=num_slots, block_size=block_size,
                  prefill_chunk=prefill_chunk,
                  decode_quantum=decode_quantum,
                  prefix_cache=prefix_cache, obs="off")

    # clean arm: greedy rows are batch-independent, so one drained run
    # over the full submission list is the per-request reference
    clean = ServingEngine(model, spec_draft=spec_draft, **kwargs)
    for s in subs:
        clean.submit(s["prompt"], max_new_tokens=s["max_new"],
                     req_id=s["req_id"])
    clean.run()
    want = {r.req_id: list(r.tokens) for r in clean.completed}
    assert clean.pool.fragmentation_stats()["blocks_in_use"] == \
        _expected_residency(clean.pool), "clean arm leaked blocks"

    # faulted arm: same submissions on their scheduled rounds, armed
    # injector + resilience, seeded mid-flight preemptions
    inj = FaultInjector(plan=plan, seed=seed, sleep=_no_sleep)
    pol = ResiliencePolicy(max_retries=2, sleep=_no_sleep,
                           spec_fault_threshold=4)
    eng = ServingEngine(model, spec_draft=spec_draft, faults=inj,
                        resilience=pol, **kwargs)
    chaos = np.random.RandomState(seed + 1)
    reqs = {}
    cursor = 0
    for rnd in range(rounds):
        while cursor < len(subs) and subs[cursor]["round"] <= rnd:
            s = subs[cursor]
            req = eng.submit(s["prompt"], max_new_tokens=s["max_new"],
                             req_id=s["req_id"])
            reqs[s["req_id"]] = req
            if s["poison"]:
                inj.poison(req.req_id)
            cursor += 1
        for _ in range(1 + int(chaos.random() < 0.4)):
            eng.step()
        if chaos.random() < 0.12:
            live = [r for r in eng.scheduler.live()
                    if not r.finished and r.slot is not None]
            if live:
                eng.preempt(live[int(chaos.randint(len(live)))])
    drain_steps = _drain(eng)

    poisoned = {s["req_id"] for s in subs if s["poison"]}
    mismatches = []
    for s in subs:
        rid = s["req_id"]
        req = reqs[rid]
        assert req.finished, f"{rid} never finished"
        assert req.finish_reason in ("eos", "stop", "length", "error"), \
            f"{rid} indefinite finish_reason {req.finish_reason!r}"
        if rid in poisoned:
            continue
        assert req.finish_reason != "error", \
            f"non-poisoned {rid} quarantined"
        if list(req.tokens) != want[rid]:
            mismatches.append(rid)
    assert not mismatches, \
        f"non-poisoned streams diverged from clean arm: {mismatches}"
    in_use = eng.pool.fragmentation_stats()["blocks_in_use"]
    assert in_use == _expected_residency(eng.pool), \
        f"faulted arm leaked blocks: {in_use} in use"
    if eng.d_pool is not None:
        d_use = eng.d_pool.fragmentation_stats()["blocks_in_use"]
        assert d_use == _expected_residency(eng.d_pool), \
            f"draft pool leaked blocks: {d_use} in use"

    rep = eng.resilience_report()
    return {
        "seed": seed,
        "rounds": rounds,
        "requests": len(subs),
        "poisoned": sorted(poisoned),
        "quarantined": rep["quarantined"],
        "faults_injected": rep["faults"]["injected_total"],
        "retries": rep["retries_total"],
        "step_skips": rep["step_skips"],
        "spec_disabled": rep["spec_disabled"],
        "pool_rebuilds": rep["pool_rebuilds"],
        "prefix_quarantines": rep["prefix_quarantines"],
        "preemptions": eng.scheduler.preempted_total,
        "drain_steps": drain_steps,
        "bitexact_streams": len(subs) - len(poisoned),
        "journal_len": len(inj.journal),
    }
