"""Cluster tier: N ServingEngine replicas behind one router — the
layer ABOVE the single-engine front door (ROADMAP item 2; reference:
the Fleet distributed-serving story and the ``paddle.distributed.
launch`` elastic layer, SURVEY.md §0).

Three routing ingredients, every one already proven per-replica:

- **Prefix-cache affinity**: the prompt's leading full blocks are
  hashed with :func:`~paddle_tpu.nlp.paged_cache.prompt_prefix_key` —
  the SAME chained FNV-1a key the pool's content-addressed index
  stores — and the key is placed on a consistent-hash ring (vnodes per
  replica), so same-system-prompt traffic lands where its blocks are
  already hot and replica add/remove moves only ~1/N of the keyspace.
- **Health-weighted balancing**: each replica exposes a cheap
  JSON-able :meth:`ClusterReplica.load_report` (burn-rate health
  state, slot/pool gauges, waiting depth); the router demotes WARN
  replicas (they lose traffic to any OK peer) and skips CRITICAL ones
  entirely, falling back to least-loaded placement when a prompt has
  no full block to be affine to.
- **Role specialization** (prefill/decode disaggregation): a
  ``role="prefill"`` replica runs the prompt phase and publishes the
  prompt's blocks into its prefix index; a ``role="decode"`` replica
  re-admits the request through the recompute-on-resume path (exactly
  :meth:`ServingEngine.restore`'s mechanism), so correctness NEVER
  depends on device-state transfer and the combined stream is
  bit-identical to a single-replica run.

:class:`ClusterFrontDoor` preserves the :class:`TokenStream` API —
callers cannot tell one replica from four — and composes the
per-engine operations cluster-wide: ``drain()`` (every accepted
request finishes), shed coordination (a request is refused only after
every eligible replica refused it), and fleet ``snapshot()`` /
``restore()`` riding the per-engine crash-recovery snapshots.

Everything here is pure host code at the same boundaries the front
door already owns: no new callbacks enter any compiled quantum, so
every golden fingerprint (``max_host_callbacks=0`` included) is
byte-identical with the cluster tier on.
"""
from __future__ import annotations

from ..nlp.paged_cache import _chain_hash, prompt_prefix_key
from .frontend import ServingFrontDoor, TokenStream
from .policy import NORMAL
from .scheduler import Request

__all__ = ["ClusterReplica", "ClusterRouter", "ClusterFrontDoor"]

_STATE_ORDER = {"ok": 0, "warn": 1, "critical": 2}


def _string_key(s):
    """64-bit chain hash of a unicode string (ring vnode placement) —
    reuses the pool's FNV-1a chain so the ring needs no new hash."""
    return _chain_hash(0, tuple(s.encode("utf-8")))


class ClusterReplica:
    """One engine + its own :class:`ServingFrontDoor` under a cluster
    router. ``role`` is ``"general"`` (default), ``"prefill"`` or
    ``"decode"``; mixed-role fleets get disaggregated hand-off."""

    def __init__(self, name, engine, role="general", policy=None,
                 door=None):
        if role not in ("general", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        self.name = str(name)
        self.engine = engine
        self.role = role
        self.door = (door if door is not None
                     else ServingFrontDoor(engine, policy=policy))

    def health_state(self, now):
        """Burn-rate health via the door's cached evaluation (no SLOs
        attached -> vacuously ``ok``)."""
        return self.door._health_state(now)

    def load_report(self, now=None):
        """Cheap, JSON-serializable load report — the poll target a
        router (in-process here, a scrape of ``/healthz`` + pool
        gauges in a multi-process deployment) balances on."""
        eng = self.engine
        if now is None:
            now = eng.obs.now()
        sched = eng.scheduler
        return {
            "replica": self.name,
            "role": self.role,
            "state": self.health_state(now),
            "waiting": len(sched.waiting),
            "live": len(sched.live()),
            "slots": int(eng.config.num_slots),
            "free_blocks": int(eng.pool.free_blocks),
            "blocks_in_use": int(eng.pool.blocks_in_use),
            "open_streams": len(self.door._streams),
            "draining": self.door.draining,
        }

    def load_score(self, now=None):
        """Sort key for least-loaded placement: waiting depth first
        (the queue is the latency), then live slots, then pool
        pressure; replica name breaks ties deterministically."""
        r = self.load_report(now)
        return (r["waiting"], r["live"], r["blocks_in_use"], self.name)


class ClusterRouter:
    """Placement policy over N replicas: prefix-affinity first, health
    always, least-loaded as the fallback.

    Args:
        replicas: list of :class:`ClusterReplica` (block sizes must
            agree — the affinity key is block-size-dependent).
        affinity_blocks: leading full blocks hashed into the affinity
            key (caps the key walk; prompts shorter than one block
            route by balance).
        vnodes: virtual nodes per replica on the consistent-hash ring.
        strategy: ``"affinity"`` (default) or ``"round_robin"`` (the
            bench's control arm: same health gating, no affinity).
        registry: a :class:`~paddle_tpu.obs.MetricsRegistry` for the
            router's own counters (default: a private one).
    """

    def __init__(self, replicas, affinity_blocks=4, vnodes=32,
                 strategy="affinity", registry=None):
        if not replicas:
            raise ValueError("a cluster needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        sizes = {r.engine.pool.block_size for r in replicas}
        if len(sizes) != 1:
            raise ValueError(
                f"replicas disagree on block_size: {sorted(sizes)} — "
                f"the affinity key would alias-route")
        if strategy not in ("affinity", "round_robin"):
            raise ValueError(f"unknown strategy {strategy!r}")
        self.replicas = list(replicas)
        self.block_size = sizes.pop()
        self.affinity_blocks = int(affinity_blocks)
        self.vnodes = int(vnodes)
        self.strategy = strategy
        if registry is None:
            from ..obs import MetricsRegistry
            registry = MetricsRegistry()
        self.registry = registry
        self._c_requests = registry.counter(
            "serving_router_requests_total",
            "Requests placed on a replica, by placement reason")
        self._c_handoffs = registry.counter(
            "serving_router_handoffs_total",
            "Disaggregated prefill->decode hand-offs")
        self._c_shed = registry.counter(
            "serving_router_shed_total",
            "Requests every eligible replica refused")
        self._c_hits = registry.counter(
            "serving_router_affinity_hits_total",
            "Keyed requests placed on the replica that last served "
            "their prefix key")
        self._c_keyed = registry.counter(
            "serving_router_affinity_lookups_total",
            "Requests that carried an affinity key")
        self._g_hit_rate = registry.gauge(
            "serving_router_affinity_hit_rate",
            "affinity_hits_total / affinity_lookups_total")
        self._g_replicas = registry.gauge(
            "serving_router_replicas",
            "Replicas on the ring, by health state")
        self._ring = []
        self._key_owner = {}   # affinity key -> replica name last placed
        self._rr_next = 0
        self._rebuild_ring()

    # -- ring --------------------------------------------------------------
    def _rebuild_ring(self):
        self._ring = sorted(
            (_string_key(f"{r.name}#{v}"), r.name)
            for r in self.replicas for v in range(self.vnodes))

    def _ring_lookup(self, key):
        """First vnode clockwise of ``key`` (wrapping) — the classic
        consistent-hash successor, so add/remove of one replica moves
        only the arcs its vnodes owned (~1/N of the keyspace)."""
        ring = self._ring
        lo, hi = 0, len(ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if ring[mid][0] < key:
                lo = mid + 1
            else:
                hi = mid
        return ring[lo % len(ring)][1]

    def _by_name(self, name):
        for r in self.replicas:
            if r.name == name:
                return r
        return None

    def add_replica(self, replica):
        """Grow the fleet: the ring is rebuilt; existing keys keep
        their owner unless the new replica's vnodes claim their arc."""
        if self._by_name(replica.name) is not None:
            raise ValueError(f"replica {replica.name!r} already routed")
        if replica.engine.pool.block_size != self.block_size:
            raise ValueError("replica block_size mismatch")
        self.replicas.append(replica)
        self._rebuild_ring()

    def remove_replica(self, name):
        """Shrink the fleet (the caller drains the replica first);
        its arcs redistribute to the ring successors."""
        rep = self._by_name(name)
        if rep is None:
            raise ValueError(f"no replica {name!r}")
        self.replicas.remove(rep)
        if not self.replicas:
            raise ValueError("cannot remove the last replica")
        self._rebuild_ring()
        return rep

    # -- placement ---------------------------------------------------------
    def now(self):
        return self.replicas[0].engine.obs.now()

    def prefix_key(self, tokens):
        return prompt_prefix_key(tokens, self.block_size,
                                 max_blocks=self.affinity_blocks)

    def plan(self, tokens, roles=None, now=None):
        """Ordered placement candidates ``[(replica, reason), ...]``:
        the head is where the request should run; the tail is the
        shed-coordination failover order. ``reason`` is ``affinity`` |
        ``balance`` | ``failover``.

        Health gating: CRITICAL replicas are skipped outright (they
        re-enter only if the WHOLE eligible fleet is critical — a
        refusal there is the per-door policy's call, not the
        router's); WARN replicas are demoted below every OK peer,
        including for affinity traffic."""
        if now is None:
            now = self.now()
        eligible = [r for r in self.replicas
                    if roles is None or r.role in roles]
        if not eligible:
            raise ValueError(f"no replica with role in {roles!r}")
        states = {r.name: r.health_state(now) for r in eligible}
        for st in ("ok", "warn", "critical"):
            self._g_replicas.set(
                sum(1 for s in states.values() if s == st), state=st)
        ok = [r for r in eligible if states[r.name] == "ok"]
        warn = [r for r in eligible if states[r.name] == "warn"]
        healthy = ok if ok else warn
        if not healthy:           # whole fleet critical: last resort
            healthy = eligible
        by_load = sorted(healthy, key=lambda r: r.load_score(now))
        if self.strategy == "round_robin":
            chosen = eligible[self._rr_next % len(eligible)]
            self._rr_next += 1
            if states[chosen.name] == "critical" and chosen not in healthy:
                chosen = by_load[0]
            rest = [r for r in by_load if r is not chosen]
            return ([(chosen, "balance")]
                    + [(r, "failover") for r in rest])
        key = self.prefix_key(tokens)
        if key is None:
            return ([(by_load[0], "balance")]
                    + [(r, "failover") for r in by_load[1:]])
        preferred = self._by_name(self._ring_lookup(key))
        if preferred is not None and preferred in healthy:
            rest = [r for r in by_load if r is not preferred]
            return ([(preferred, "affinity")]
                    + [(r, "failover") for r in rest])
        # preferred ineligible / demoted / critical: fail over by load
        return [(r, "failover") for r in by_load]

    def note_placement(self, tokens, replica, reason):
        """Account the ACTUAL placement (after shed failover): request
        counter, affinity hit bookkeeping, hit-rate gauge."""
        self._c_requests.inc(replica=replica.name, reason=reason)
        key = self.prefix_key(tokens)
        if key is None:
            return
        self._c_keyed.inc()
        if self._key_owner.get(key) == replica.name:
            self._c_hits.inc()
        self._key_owner[key] = replica.name
        keyed = self._c_keyed.value()
        if keyed:
            self._g_hit_rate.set(self._c_hits.value() / keyed)

    def note_shed(self, reason):
        self._c_shed.inc(reason=str(reason))

    def note_handoff(self):
        self._c_handoffs.inc()

    # -- views -------------------------------------------------------------
    @property
    def roles(self):
        return {r.role for r in self.replicas}

    @property
    def disaggregated(self):
        return "prefill" in self.roles and "decode" in self.roles

    def load_reports(self, now=None):
        if now is None:
            now = self.now()
        return [r.load_report(now) for r in self.replicas]

    def affinity_stats(self):
        keyed = self._c_keyed.value()
        return {
            "keys_tracked": len(self._key_owner),
            "keyed_requests": int(keyed),
            "affinity_hits": int(self._c_hits.value()),
            "hit_rate": (self._c_hits.value() / keyed) if keyed else 0.0,
        }


class ClusterFrontDoor:
    """The :class:`TokenStream` API over a routed fleet. ``submit``
    places each request through the router's plan, trying candidates
    in order until one admits (shed coordination: the caller sees
    ``finish_reason == "shed"`` only when EVERY eligible replica
    refused); on a disaggregated fleet, greedy requests without stop
    sequences run the prefill phase on a prefill replica and hand off
    to a decode replica via recompute-on-resume."""

    def __init__(self, router):
        self.router = router
        self._draining = False
        self._seq = 0

    @property
    def replicas(self):
        return self.router.replicas

    @property
    def engines(self):
        return [r.engine for r in self.replicas]

    # -- submission --------------------------------------------------------
    def submit(self, prompt, max_new_tokens=32, priority=NORMAL,
               temperature=None, stop_token_ids=None,
               stop_sequences=None, seed=0, req_id=None, timeout=None):
        """Route-and-admit one request; always returns a stream with
        the single-door contract (check ``stream.shed``)."""
        tokens = [int(t) for t in prompt]
        router = self.router
        if req_id is None:
            req_id = f"c{self._seq}"
        self._seq += 1
        if (router.disaggregated and not self._draining
                and max_new_tokens > 1
                and (temperature is None or temperature == 0)
                and not stop_sequences):
            return self._submit_handoff(
                tokens, max_new_tokens, priority, stop_token_ids,
                seed, req_id, timeout)
        roles = (("decode", "general") if router.disaggregated
                 else None)
        return self._routed_submit(
            tokens, roles, max_new_tokens=max_new_tokens,
            priority=priority, temperature=temperature,
            stop_token_ids=stop_token_ids,
            stop_sequences=stop_sequences, seed=seed, req_id=req_id,
            timeout=timeout)

    def _routed_submit(self, tokens, roles, **kw):
        """Try the plan's candidates in order; the first non-shed
        stream wins. Candidate i>0 is accounted as ``failover``
        regardless of its planned reason — the head refused it."""
        router = self.router
        plan = router.plan(tokens, roles=roles)
        stream = None
        for i, (rep, reason) in enumerate(plan):
            stream = rep.door.submit(tokens, **kw)
            if not stream.shed:
                reason = reason if i == 0 else "failover"
                router.note_placement(tokens, rep, reason)
                self._journal_route(rep, stream.request, reason)
                return stream
        router.note_shed("cluster_full" if not self._draining
                         else "draining")
        return stream

    def _journal_route(self, rep, req, reason):
        flight = rep.engine.flight
        if flight is not None:
            flight.on_route(req, rep.engine.obs.now(),
                            replica=rep.name, reason=reason)

    def _submit_handoff(self, tokens, max_new_tokens, priority,
                        stop_token_ids, seed, req_id, timeout):
        """Disaggregated path: prefill replica emits the first token
        (publishing the prompt's blocks into ITS prefix index for the
        next same-prefix arrival), then the decode replica re-admits
        prompt+[t0] through the recompute-on-resume path — the exact
        :meth:`ServingFrontDoor.restore` mechanism, so the combined
        stream is bit-identical to a single-replica run."""
        router = self.router
        pre = self._routed_submit(
            tokens, ("prefill",), max_new_tokens=1, priority=priority,
            stop_token_ids=stop_token_ids, seed=seed,
            req_id=f"{req_id}#prefill")
        if pre.shed:
            return pre
        first = pre.result()          # pumps the prefill door to done
        if (len(first) == 0 or pre.request.finish_reason
                in ("eos", "stop", "error")):
            return pre                # finished inside the prefill leg
        t0 = int(first[-1])
        # decode-side re-admission (force-admit: the cluster accepted
        # this request at the prefill leg; drain semantics owe it a
        # finish)
        plan = router.plan(tokens, roles=("decode",))
        rep, reason = plan[0]
        eng = rep.engine
        now = eng.obs.now()
        req = Request(tokens, max_new_tokens=max_new_tokens,
                      req_id=req_id, seed=seed, priority=priority,
                      stop_token_ids=stop_token_ids, arrival_time=now)
        req.tokens = [t0]
        req.begin_resume()
        eng.scheduler.submit(req)
        eng._on_submitted(req)
        router.note_placement(tokens, rep, reason)
        router.note_handoff()
        self._journal_route(rep, req, reason)
        if eng.flight is not None:
            eng.flight.on_handoff(req, now, src=pre.request.req_id,
                                  dst=rep.name,
                                  tokens_prefilled=len(tokens) + 1)
        stream = TokenStream(req, rep.door, timeout=timeout)
        stream._buf.append(t0)
        rep.door._streams[str(req.req_id)] = stream
        return stream

    # -- the pump ----------------------------------------------------------
    def pump(self):
        """One iteration of EVERY replica's front door — OVERLAPPED:
        dispatch every replica's quantum first (JAX dispatch is async,
        so each device starts executing immediately), then collect in
        the same order. N replica devices run concurrently under one
        pump pass instead of each replica's host work serializing on
        the previous replica's device wall. True while any replica
        still has work."""
        pend = []
        for rep in self.replicas:
            if rep.engine.has_work:
                pend.append((rep, rep.door.pump_dispatch()))
        alive = False
        for rep, p in pend:
            alive = rep.door.pump_collect(p) or alive
        return alive

    @property
    def has_work(self):
        return any(eng.has_work for eng in self.engines)

    def run_until_idle(self):
        """Drive the whole fleet synchronously until idle; returns the
        per-replica completed lists keyed by replica name."""
        while self.has_work:
            self.pump()
        return {r.name: r.engine.completed for r in self.replicas}

    # -- cluster-wide operations -------------------------------------------
    def drain(self, flight_dir=None):
        """Coordinated drain: every door stops accepting FIRST (so a
        submission racing the drain sheds everywhere instead of
        landing on a not-yet-draining replica), then the whole fleet
        pumps INTERLEAVED until idle — one overlapped pass per replica
        per round via :meth:`pump`, never one replica to completion
        before the next starts (the old ring-order drain starved later
        replicas: replica 0 ran its whole backlog while replica N-1's
        accepted requests aged). Each door's ``drain()`` then runs on
        an already-idle engine, contributing only its summary + flight
        flush. Returns per-replica summaries + fleet totals."""
        import os
        self._draining = True
        for rep in self.replicas:       # flip all gates before pumping
            if not rep.door.draining:
                rep.door._draining = True
                eng = rep.engine
                eng.obs.on_drain(eng.obs.now(),
                                 live=len(eng.scheduler.live()),
                                 waiting=len(eng.scheduler.waiting))
        while self.has_work:            # interleaved, fleet-wide
            self.pump()
        out = {"drained": True, "replicas": {}}
        completed = shed = 0
        for rep in self.replicas:
            path = (os.path.join(flight_dir, f"{rep.name}.jsonl")
                    if flight_dir is not None
                    and rep.engine.flight is not None else None)
            s = rep.door.drain(flight_path=path)
            out["replicas"][rep.name] = s
            completed += s["completed"]
            shed += s["shed"]
        out["completed"] = completed
        out["shed"] = shed
        return out

    @property
    def draining(self):
        return self._draining

    # -- fleet crash recovery ----------------------------------------------
    def snapshot(self):
        """Fleet snapshot: every replica's engine snapshot (PR 13's
        crash-recovery schema) plus the router's placement state, so a
        restored cluster keeps its affinity map warm."""
        router = self.router
        return {
            "version": 1,
            "kind": "serving_cluster_snapshot",
            "strategy": router.strategy,
            "affinity_blocks": router.affinity_blocks,
            "vnodes": router.vnodes,
            "rr_next": router._rr_next,
            "affinity_map": {str(k): v
                             for k, v in router._key_owner.items()},
            "replicas": [{"name": r.name, "role": r.role,
                          "snapshot": r.engine.snapshot()}
                         for r in self.replicas],
        }

    @classmethod
    def restore(cls, snap, model, policy=None, registry=None,
                spec_draft=None, **overrides):
        """Rebuild the whole fleet from a snapshot: each replica
        restores through :meth:`ServingFrontDoor.restore` (in-flight
        requests re-admitted via recompute-on-resume with pre-loaded
        streams), and the router resumes with the saved affinity map.
        ``model`` is one shared model, or a dict ``{replica_name:
        model}`` for heterogeneous fleets."""
        if snap.get("kind") != "serving_cluster_snapshot":
            raise ValueError(
                f"not a cluster snapshot: kind={snap.get('kind')!r}")
        reps = []
        for r in snap["replicas"]:
            m = model[r["name"]] if isinstance(model, dict) else model
            door = ServingFrontDoor.restore(
                r["snapshot"], m, policy=policy,
                spec_draft=spec_draft, **overrides)
            reps.append(ClusterReplica(r["name"], door.engine,
                                       role=r["role"], door=door))
        router = ClusterRouter(
            reps, affinity_blocks=snap["affinity_blocks"],
            vnodes=snap["vnodes"], strategy=snap["strategy"],
            registry=registry)
        router._rr_next = int(snap.get("rr_next", 0))
        router._key_owner = {int(k): v
                             for k, v in snap["affinity_map"].items()}
        return cls(router)

    # -- views -------------------------------------------------------------
    def streams(self):
        """All open streams across the fleet, keyed by req_id."""
        out = {}
        for rep in self.replicas:
            out.update(rep.door._streams)
        return out

    def stats(self):
        """Fleet stats: per-replica front-door stats + router affinity
        view + fleet totals."""
        per = {r.name: r.door.stats() for r in self.replicas}
        return {
            "replicas": per,
            "router": self.router.affinity_stats(),
            "admitted": sum(s["admitted"] for s in per.values()),
            "finished": sum(s["finished"] for s in per.values()),
            "shed": sum(s["shed"] for s in per.values()),
            "draining": self._draining,
        }
