"""Continuous-batching serving engine over the paged KV pool.

The reference's serving story is the decode HOT LOOP that admits and
retires ragged requests against a shared KV cache (AnalysisPredictor /
``Predictor.run`` -> fused_multi_transformer, SURVEY.md §2.6/§3.5;
the blocked-cache serving predictor is unverified, SURVEY §0). The TPU
shape of that loop:

- **fixed-capacity slot batch**: the decode step is compiled ONCE for
  ``num_slots`` rows (the padded active set). Requests occupy slots;
  empty/finished slots ride along masked. No recompiles as traffic
  ebbs and flows.
- **single-dispatch decode quantum**: ``decode_quantum`` tokens for
  every live slot run inside ONE jitted program — a ``lax.scan`` of
  single-token steps over the shared
  :class:`~paddle_tpu.nlp.paged_cache.PagedKVCachePool`, with
  eos/max-len retirement masks computed ON DEVICE and the pool buffers
  donated (audited by the ``serving_decode_step`` analysis Budget: zero
  involuntary remat, zero host callbacks, pools donated). The host
  scheduler runs only at quantum boundaries.
- **chunked prefill interleaved with decode**: new arrivals push their
  prompt through ``block_multihead_attention`` in ``prefill_chunk``-
  token slices, sharing MIXED batches with the in-flight slots' decode
  rows — admission never stalls the running requests.
- **block accounting**: retirement returns blocks to the pool free
  list for immediate reuse; admission is gated on worst-case demand so
  the pool cannot exhaust mid-flight (scheduler.py).

Token selection reuses the generation tier's ``_filter_logits``
(greedy argmax or temperature/top-k/top-p sampling with per-slot key
fold-in); the greedy arm is oracle-tested bit-exact against
per-request sequential ``generate`` (tests/test_serving.py).
"""
from __future__ import annotations

import math
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import autograd
from ..jit import functional_call
from ..nlp.generation import _filter_logits
from ..nlp.paged_cache import PagedKVCachePool
from .scheduler import Request, Scheduler, SchedulerConfig

__all__ = ["ServingEngine"]


def _rope_rows(x, cos, sin):
    """Rotate (S, H, D) by per-row angles (S, D/2) — the model's
    default (neox) rotary layout at each slot's own cache position."""
    xf = x.astype(jnp.float32)
    c = cos[:, None, :]
    s = sin[:, None, :]
    d = x.shape[-1]
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def _xla_paged_decode_attn(q, kp, vp, tables, lens):
    """Off-TPU decode attention over the paged pool: gather the table's
    blocks and run the same f32 masked softmax as the contiguous-cache
    fallback (`_masked_decode_attn`)."""
    s_, h, d = q.shape
    w = tables.shape[1]
    bs, hk = kp.shape[1], kp.shape[2]
    k = kp[tables].reshape(s_, w * bs, hk, d)
    v = vp[tables].reshape(s_, w * bs, hk, d)
    rep = h // hk
    kr = jnp.repeat(k, rep, axis=2) if rep > 1 else k
    vr = jnp.repeat(v, rep, axis=2) if rep > 1 else v
    sc = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                        kr.astype(jnp.float32)) * sc
    mask = jnp.arange(w * bs)[None, :] < lens[:, None]
    logits = jnp.where(mask[:, None, :], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhk,bkhd->bhd", p, vr.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_attn(q, kp, vp, tables, lens):
    """Route decode attention: Pallas paged kernel on TPU (block tables
    dereferenced in SMEM, one pool block DMA per grid step), XLA gather
    fallback elsewhere."""
    from ..core.flags import get_flags

    flags = get_flags(["FLAGS_use_pallas_kernels", "FLAGS_pallas_force"])
    use_pallas = flags["FLAGS_use_pallas_kernels"] and (
        jax.default_backend() == "tpu" or flags["FLAGS_pallas_force"])
    if use_pallas:
        from ..ops.pallas.paged_attention import paged_decode_attention

        return paged_decode_attention(q, kp, vp, tables, lens)
    return _xla_paged_decode_attn(q, kp, vp, tables, lens)


class _AuditedStep:
    """Callable+lowerable wrapper handed to ``analysis.check_budget``:
    declares how many LEADING flat args the quantum donates (the 2L KV
    pool leaves) so ``require_donated`` audits the right set."""

    def __init__(self, jitted, n_donatable):
        self._jitted = jitted
        self.n_donatable = int(n_donatable)
        self.__name__ = "serving_decode_quantum"

    def __call__(self, *args):
        return self._jitted(*args)

    def lower(self, *args):
        return self._jitted.lower(*args)


class ServingEngine:
    """Multiplex many in-flight generation requests over one shared
    paged KV pool and one jitted decode step.

    Args:
        model: a LlamaForCausalLM-shaped causal LM (eval mode; params
            define the cache dtype).
        num_slots: fixed decode batch capacity (padded active set).
        block_size: KV pool block size in tokens.
        num_blocks: pool capacity; default sizes the pool for
            ``num_slots`` full-context sequences plus the scratch block.
        max_context: per-request prompt+generation bound (defaults to
            the model's max_position_embeddings).
        prefill_chunk / decode_quantum: see SchedulerConfig.
        decode_strategy: "greedy" | "sampling" (engine-wide; sampling
            knobs via top_k/top_p/temperature, per-request seeds).
        eos_token_id: retire a slot the step after it emits this id.
    """

    def __init__(self, model, num_slots=8, block_size=32, num_blocks=None,
                 max_context=None, prefill_chunk=64, decode_quantum=8,
                 decode_strategy="greedy", top_k=0, top_p=1.0,
                 temperature=1.0, eos_token_id=None):
        cfg = model.config
        if getattr(cfg, "sliding_window", None):
            raise NotImplementedError(
                "ServingEngine does not compose with sliding_window: a "
                "rolling buffer wrap-writes over pool slots the block "
                "tables still map")
        if decode_strategy not in ("greedy", "sampling"):
            raise ValueError(
                f"decode_strategy must be greedy|sampling, got "
                f"{decode_strategy!r}")
        self.model = model
        model.eval()
        self.config = SchedulerConfig(num_slots=num_slots,
                                      prefill_chunk=prefill_chunk,
                                      decode_quantum=decode_quantum)
        self.decode_strategy = decode_strategy
        self.top_k = 0 if top_k is None else int(top_k)
        self.top_p = 1.0 if top_p is None else float(top_p)
        self.temperature = 1.0 if temperature is None else float(temperature)
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))

        self.max_context = int(max_context
                               or cfg.max_position_embeddings)
        self._p_vals = [p._value for _, p in model.named_parameters()]
        cache_dtype = self._p_vals[0].dtype
        s = self.config.num_slots
        bs = int(block_size)
        w = -(-self.max_context // bs)
        if num_blocks is None:
            num_blocks = s * w + 1  # +1: the masked-write scratch block
        self.pool = PagedKVCachePool(
            num_blocks, bs, cfg.num_key_value_heads, cfg.head_dim,
            num_layers=cfg.num_hidden_layers, dtype=cache_dtype)
        # masked (retired/empty) rows dump their KV writes here
        self._scratch_block = self.pool.ensure("__scratch__", 1)[0]
        self.scheduler = Scheduler(self.config, self.pool,
                                   reserved_blocks=1)
        self._table_width = w

        # host mirrors of the per-slot device state
        self._tables = np.zeros((s, w), np.int32)
        self._seq_lens = np.zeros(s, np.int32)
        self._last_tok = np.zeros(s, np.int32)
        self._n_gen = np.zeros(s, np.int32)
        self._done = np.ones(s, bool)
        self._max_new = np.zeros(s, np.int32)
        self._keys = np.zeros((s, 2), np.uint32)

        # rotary table shared by prefill (block_mha fused rope) and the
        # quantum (per-row angles recomputed on device)
        from ..nn.functional.rope import build_rope_cache

        cos, sin = build_rope_cache(self.max_context, cfg.head_dim,
                                    base=cfg.rope_theta)
        self._rotary = Tensor(jnp.stack([cos, sin]), stop_gradient=True)

        self._quantum = jax.jit(self._make_quantum(),
                                donate_argnums=(0, 1))
        self._audited = _AuditedStep(
            self._quantum, n_donatable=2 * cfg.num_hidden_layers)
        self.completed: list = []
        self.stats = {"steps": 0, "mixed_steps": 0, "decode_quanta": 0,
                      "quantum_tokens": 0, "prefill_tokens": 0,
                      "generated_tokens": 0, "occupancy_sum": 0.0}

    # -- public API --------------------------------------------------------
    def submit(self, prompt, max_new_tokens=32, req_id=None, seed=0,
               arrival_time=None):
        """Queue one request; returns the :class:`Request` handle."""
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      req_id=req_id, seed=seed,
                      arrival_time=(time.perf_counter()
                                    if arrival_time is None
                                    else arrival_time))
        total = req.prompt_len + req.max_new_tokens
        if total > self.max_context:
            raise ValueError(
                f"request needs {total} tokens > max_context "
                f"{self.max_context}")
        return self.scheduler.submit(req)

    @property
    def has_work(self):
        return self.scheduler.has_work

    def step(self):
        """One scheduler iteration: admit, then either a mixed
        prefill(+decode) step or a jitted decode quantum, then retire."""
        self.stats["steps"] += 1
        self._admit()
        live = self.scheduler.live()
        self.stats["occupancy_sum"] += (
            len(live) / self.config.num_slots)
        if self.scheduler.prefilling():
            self._mixed_step()
        elif self.scheduler.decoding():
            self._decode_quantum()
        return self.scheduler.has_work

    def run(self, requests=None):
        """Submit ``requests`` (if given) and drive until idle; returns
        the completed :class:`Request` list in submission order."""
        if requests is not None:
            for r in requests:
                if isinstance(r, Request):
                    self.scheduler.submit(r)
                elif isinstance(r, dict):
                    self.submit(**r)
                else:
                    self.submit(r)
        while self.step():
            pass
        return self.completed

    def output_tokens(self, req):
        """prompt + generated ids as one int32 array (generate()-style
        row, truncated at retirement rather than pad-filled)."""
        return np.concatenate([req.prompt,
                               np.asarray(req.tokens, np.int32)])

    def engine_stats(self):
        out = dict(self.stats)
        out["pool"] = self.pool.fragmentation_stats()
        out["admitted"] = self.scheduler.admitted_total
        out["finished"] = self.scheduler.finished_total
        if self.stats["steps"]:
            out["mean_occupancy"] = (self.stats["occupancy_sum"]
                                     / self.stats["steps"])
        return out

    def decode_step_target(self):
        """(auditable step, example args) for ``analysis.check_budget``
        — the EXACT compiled object the serving hot loop dispatches,
        with the engine's live state as the example batch."""
        return self._audited, self._quantum_args()

    # -- admission + prefill ----------------------------------------------
    def _admit(self):
        now = time.perf_counter()
        for req in self.scheduler.try_admit():
            req.admit_time = now
            slot = req.slot
            self._seq_lens[slot] = 0
            self._n_gen[slot] = 0
            self._done[slot] = True  # not decodable until prefill ends
            self._max_new[slot] = req.max_new_tokens
            self._keys[slot] = np.asarray(jax.random.PRNGKey(req.seed))

    def _mixed_step(self):
        """One chunk of prefill for every prefilling slot, one decode
        token for every in-flight slot — a single MIXED batch through
        ``block_multihead_attention`` per layer (chunked prefill
        interleaved with decode, the reference's serving batch shape)."""
        import paddle_tpu as paddle
        from ..incubate.nn.functional import block_multihead_attention

        self.stats["mixed_steps"] += 1
        model, cfg = self.model, self.model.config
        chunk = self.config.prefill_chunk
        pre = self.scheduler.prefilling()
        dec = self.scheduler.decoding()
        rows = pre + dec
        toks, this_time, enc_lens, dec_lens = [], [], [], []
        for req in pre:
            n = min(chunk, req.prompt_len - req.prefill_pos)
            toks.append(req.prompt[req.prefill_pos:req.prefill_pos + n])
            this_time.append(n)
            enc_lens.append(n)
            dec_lens.append(req.prefill_pos)
            self.pool.ensure(req.req_id, req.prefill_pos + n)
        for req in dec:
            slot = req.slot
            toks.append(np.asarray([self._last_tok[slot]], np.int32))
            this_time.append(1)
            enc_lens.append(0)
            dec_lens.append(int(self._seq_lens[slot]))
            self.pool.ensure(req.req_id, int(self._seq_lens[slot]) + 1)
        ids = np.concatenate(toks).astype(np.int32)
        total = int(ids.shape[0])
        self.stats["prefill_tokens"] += int(sum(enc_lens))
        cu = np.concatenate([[0], np.cumsum(this_time)]).astype(np.int32)
        tables = self.pool.block_table_array(
            [r.req_id for r in rows], pad_to=self._table_width)

        h, hk, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                    cfg.head_dim)
        kc_t = [Tensor(self.pool.k_pools[i], stop_gradient=True)
                for i in range(cfg.num_hidden_layers)]
        vc_t = [Tensor(self.pool.v_pools[i], stop_gradient=True)
                for i in range(cfg.num_hidden_layers)]
        common = dict(
            seq_lens_encoder=paddle.to_tensor(
                np.asarray(enc_lens, np.int32)),
            seq_lens_decoder=paddle.to_tensor(
                np.asarray(dec_lens, np.int32)),
            seq_lens_this_time=paddle.to_tensor(
                np.asarray(this_time, np.int32)),
            block_tables=Tensor(tables, stop_gradient=True),
            rotary_embs=self._rotary,
            use_neox_rotary_style=True,  # the model's rope layout
            num_heads=h, kv_num_heads=hk, head_dim=d,
        )
        with autograd.no_grad():
            core = model.llama
            hidden = core.embed_tokens(
                paddle.to_tensor(ids[None, :]))          # (1, T, E)
            for i, layer in enumerate(core.layers):
                attn = layer.self_attn
                residual = hidden
                x = layer.input_layernorm(hidden)
                q = attn.q_proj(x)
                k = attn.k_proj(x)
                v = attn.v_proj(x)
                qkv = paddle.concat([q, k, v], axis=-1) \
                    .reshape([total, (h + 2 * hk) * d])
                att = block_multihead_attention(
                    qkv, kc_t[i], vc_t[i], **common)
                att3 = att.reshape([1, total, h * d])
                hidden = residual + attn.o_proj(att3)
                hidden = hidden + layer.mlp(
                    layer.post_attention_layernorm(hidden))
            hidden = core.norm(hidden)
        # the mutated pool Tensors are the new truth
        for i in range(cfg.num_hidden_layers):
            self.pool.k_pools[i] = kc_t[i]._value
            self.pool.v_pools[i] = vc_t[i]._value

        # logits only where a next token is due: rows completing their
        # prefill this chunk, and every decode row
        need = [i for i, req in enumerate(rows)
                if (i >= len(pre)) or
                (req.prefill_pos + this_time[i] >= req.prompt_len)]
        if need:
            last_idx = np.asarray([cu[i + 1] - 1 for i in need], np.int32)
            with autograd.no_grad():
                hs = Tensor(hidden._value[0, last_idx],
                            stop_gradient=True)
                logits = model.lm_head(hs)._value        # (R, V)
            nxt = self._select_host(logits,
                                    [rows[i] for i in need])
        now = time.perf_counter()
        for i, req in enumerate(rows):
            slot = req.slot
            if i < len(pre):
                req.prefill_pos += this_time[i]
                self._seq_lens[slot] = req.prefill_pos
                if req.prefill_pos >= req.prompt_len:
                    tok = int(nxt[need.index(i)])
                    req.first_token_time = now
                    req.record(tok, self.eos_token_id)
                    self._record_host(slot, req, tok)
            else:
                tok = int(nxt[need.index(i)])
                self._seq_lens[slot] += 1  # last_tok entered the cache
                req.record(tok, self.eos_token_id)
                self._record_host(slot, req, tok)
        self._retire_finished()

    def _record_host(self, slot, req, tok):
        self._last_tok[slot] = tok
        self._n_gen[slot] = len(req.tokens)
        self._done[slot] = req.finished

    def _select_host(self, logits, rows):
        """First-token / mixed-step selection with the SAME math as the
        device quantum: argmax, or filtered categorical keyed by each
        slot's fold_in(key, n_emitted)."""
        if self.decode_strategy == "greedy":
            return np.asarray(jnp.argmax(logits, axis=-1)).astype(np.int32)
        filt = _filter_logits(logits, self.top_k, self.top_p,
                              self.temperature)
        keys = jnp.asarray(np.stack(
            [self._keys[r.slot] for r in rows]))
        steps = jnp.asarray(np.asarray(
            [len(r.tokens) for r in rows], np.int32))
        keys = jax.vmap(jax.random.fold_in)(keys, steps)
        samp = jax.vmap(jax.random.categorical)(keys, filt)
        return np.asarray(samp).astype(np.int32)

    # -- the jitted decode quantum ----------------------------------------
    def _select_device(self, logits, keys, n_gen):
        if self.decode_strategy == "greedy":
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        filt = _filter_logits(logits, self.top_k, self.top_p,
                              self.temperature)
        step_keys = jax.vmap(jax.random.fold_in)(keys, n_gen)
        return jax.vmap(jax.random.categorical)(
            step_keys, filt).astype(jnp.int32)

    def _paged_decode_math(self, ids_t, seq_lens, tables, kc, vc, live):
        """One token for every slot over the paged pool (the quantum's
        per-step body; mirrors generation._manual_decode with block-table
        writes instead of dense-cache slice updates)."""
        model, cfg = self.model, self.model.config
        core = model.llama
        s = ids_t.shape[0]
        h, hk, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                    cfg.head_dim)
        bs = self.pool.block_size
        w = tables.shape[1]

        hidden = core.embed_tokens(ids_t)                # (S, 1, E)
        inv_freq = 1.0 / (cfg.rope_theta ** (
            jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        pos = seq_lens.astype(jnp.float32)
        freqs = pos[:, None] * inv_freq[None, :]
        cos, sin = jnp.cos(freqs), jnp.sin(freqs)        # (S, D/2)

        blk_idx = jnp.clip(seq_lens // bs, 0, w - 1)
        own_blk = jnp.take_along_axis(tables, blk_idx[:, None],
                                      axis=1)[:, 0]
        write_blk = jnp.where(live, own_blk, self._scratch_block)
        write_off = jnp.where(live, seq_lens % bs, 0)
        lens = jnp.where(live, seq_lens + 1, 1)

        new_kc, new_vc = [], []
        for i, layer in enumerate(core.layers):
            attn = layer.self_attn
            residual = hidden
            x = layer.input_layernorm(hidden)
            q = attn.q_proj(x).reshape([s, 1, h, d])
            k = attn.k_proj(x).reshape([s, 1, hk, d])
            v = attn.v_proj(x).reshape([s, 1, hk, d])
            qv = _rope_rows(q._value[:, 0], cos, sin)    # (S, H, D)
            kv = _rope_rows(k._value[:, 0], cos, sin)
            kci = kc[i].at[write_blk, write_off].set(
                kv.astype(kc[i].dtype))
            vci = vc[i].at[write_blk, write_off].set(
                v._value[:, 0].astype(vc[i].dtype))
            new_kc.append(kci)
            new_vc.append(vci)
            att = _paged_attn(qv, kci, vci, tables, lens)
            att_t = Tensor(att.reshape(s, 1, h * d), stop_gradient=True)
            hidden = residual + attn.o_proj(att_t)
            hidden = hidden + layer.mlp(
                layer.post_attention_layernorm(hidden))
        hidden = core.norm(hidden)
        logits = model.lm_head(hidden)
        return logits._value[:, 0], new_kc, new_vc

    def _make_quantum(self):
        model = self.model
        t_steps = self.config.decode_quantum
        has_eos = self.eos_token_id is not None
        eos = -1 if self.eos_token_id is None else int(self.eos_token_id)

        def quantum(kc, vc, p_vals, tables, seq_lens, last_tok, n_gen,
                    done, max_new, keys):
            def body(carry, _):
                kc, vc, seq_lens, last_tok, n_gen, done = carry
                live = ~done
                with autograd.no_grad():
                    def fwd(tok_t):
                        return self._paged_decode_math(
                            tok_t, seq_lens, tables, kc, vc, live)

                    (logits, kc2, vc2), _ = functional_call(
                        model, fwd,
                        [Tensor(last_tok[:, None], stop_gradient=True)],
                        {}, p_vals, [])
                nxt = self._select_device(logits, keys, n_gen)
                nxt = jnp.where(done, last_tok, nxt).astype(jnp.int32)
                n_gen2 = n_gen + live.astype(jnp.int32)
                done2 = done | (n_gen2 >= max_new)
                if has_eos:
                    done2 = done2 | (live & (nxt == eos))
                seq_lens2 = seq_lens + live.astype(jnp.int32)
                return (kc2, vc2, seq_lens2, nxt, n_gen2, done2), nxt

            (kc, vc, seq_lens, last_tok, n_gen, done), toks = \
                jax.lax.scan(
                    body, (kc, vc, seq_lens, last_tok, n_gen, done),
                    None, length=t_steps)
            return kc, vc, seq_lens, last_tok, n_gen, done, toks

        return quantum

    def _quantum_args(self):
        return (list(self.pool.k_pools), list(self.pool.v_pools),
                self._p_vals, jnp.asarray(self._tables),
                jnp.asarray(self._seq_lens),
                jnp.asarray(self._last_tok), jnp.asarray(self._n_gen),
                jnp.asarray(self._done), jnp.asarray(self._max_new),
                jnp.asarray(self._keys))

    def _decode_quantum(self):
        """Dispatch one jitted quantum; the single host sync per
        ``decode_quantum`` tokens happens HERE, at the admit/retire
        boundary, never inside the compiled loop."""
        self.stats["decode_quanta"] += 1
        t_steps = self.config.decode_quantum
        # grow each live slot's block table to cover the quantum before
        # entering the device loop (tables are static inside)
        for req in self.scheduler.decoding():
            slot = req.slot
            cap = req.prompt_len + req.max_new_tokens - 1
            need = min(int(self._seq_lens[slot]) + t_steps, cap)
            if need > self.pool.seq_len(req.req_id):
                self.pool.ensure(req.req_id, need)
            row = self.pool.block_table_array(
                [req.req_id], pad_to=self._table_width)
            self._tables[slot] = np.asarray(row)[0][:self._table_width]
        kc, vc, seq_lens, last_tok, n_gen, done, toks = self._quantum(
            *self._quantum_args())
        self.pool.k_pools = list(kc)
        self.pool.v_pools = list(vc)
        toks = np.asarray(toks)                          # (T, S) sync
        self._seq_lens = np.asarray(seq_lens).copy()
        self._last_tok = np.asarray(last_tok).copy()
        self._n_gen = np.asarray(n_gen).copy()
        self._done = np.asarray(done).copy()
        self.stats["quantum_tokens"] += int(toks.shape[0]) * int(
            toks.shape[1])
        now = time.perf_counter()
        for req in self.scheduler.decoding():
            slot = req.slot
            for k in range(toks.shape[0]):
                if req.finished:
                    break
                req.record(int(toks[k, slot]), self.eos_token_id)
            if req.finished:
                req.finish_time = now
        self._retire_finished()

    def _retire_finished(self):
        now = time.perf_counter()
        for req in list(self.scheduler.live()):
            if req.finished:
                slot = req.slot
                if req.finish_time is None:
                    req.finish_time = now
                self.stats["generated_tokens"] += len(req.tokens)
                self._done[slot] = True
                self._max_new[slot] = 0
                self.scheduler.retire(req)
                self.completed.append(req)
