"""Continuous-batching serving engine over the paged KV pool.

The reference's serving story is the decode HOT LOOP that admits and
retires ragged requests against a shared KV cache (AnalysisPredictor /
``Predictor.run`` -> fused_multi_transformer, SURVEY.md §2.6/§3.5;
the blocked-cache serving predictor is unverified, SURVEY §0). The TPU
shape of that loop:

- **fixed-capacity slot batch**: the decode step is compiled ONCE for
  ``num_slots`` rows (the padded active set). Requests occupy slots;
  empty/finished slots ride along masked. No recompiles as traffic
  ebbs and flows.
- **single-dispatch decode quantum**: ``decode_quantum`` tokens for
  every live slot run inside ONE jitted program — a ``lax.scan`` of
  single-token steps over the shared
  :class:`~paddle_tpu.nlp.paged_cache.PagedKVCachePool`, with
  eos/max-len retirement masks computed ON DEVICE and the pool buffers
  donated (audited by the ``serving_decode_step`` analysis Budget: zero
  involuntary remat, zero host callbacks, pools donated). The host
  scheduler runs only at quantum boundaries.
- **chunked prefill interleaved with decode**: new arrivals push their
  prompt through ``block_multihead_attention`` in ``prefill_chunk``-
  token slices, sharing MIXED batches with the in-flight slots' decode
  rows — admission never stalls the running requests.
- **block accounting**: retirement returns blocks to the pool free
  list for immediate reuse; admission is gated on worst-case demand so
  the pool cannot exhaust mid-flight (scheduler.py).

Token selection reuses the generation tier's ``_filter_logits``
(greedy argmax or temperature/top-k/top-p sampling with per-slot key
fold-in); the greedy arm is oracle-tested bit-exact against
per-request sequential ``generate`` (tests/test_serving.py).

With ``spec_draft`` the decode quantum becomes the ON-DEVICE
speculative round (serving/speculative.py): a second (draft) paged
pool rides the same scheduler — admission gates on both pools plus the
verify-write margin, chunked prefill pushes the same mixed batches
through the draft, and one jitted dispatch per round covers draft-γ
scan + target verify + in-graph acceptance with BOTH pools donated.

Runtime observability rides the SAME boundaries the host scheduler
already owns (paddle_tpu/obs): ``engine.obs`` carries the metrics
registry (TTFT/e2e/inter-token histograms, windowed tok/s, acceptance
rate, pool gauges — ``engine.stats`` is a thin compatibility view over
its counters) and, with ``trace=True``, a Chrome trace-event recorder
(per-slot request spans + quantum spans, Perfetto-loadable). Because
every hook runs at a quantum/step boundary on the host, the jitted
programs keep ``max_host_callbacks=0`` and byte-identical golden
fingerprints with observability enabled — asserted by the
``serving_decode_step`` / ``speculative_verify_step`` recipes, which
build THIS engine with full instrumentation on.

The operability tier rides the same boundaries: ``slo=`` attaches
declarative objectives evaluated with multi-window burn rates
(``engine.health()``, served live by obs/export.py's ``/healthz`` /
``/slo``), and ``flight=`` a per-request flight recorder whose
journals dump on SLO-threshold crossings (obs/flight.py) — so a slow
tail request is explainable, not just a histogram bucket.

The FRONT DOOR (serving/frontend.py + serving/policy.py) wraps this
engine into the serving *system*: token-by-token streaming (the
``token_sink`` hook below fires per emitted token), priority classes
with :meth:`preempt` (evict a victim's blocks back to the pool,
recompute-on-resume), SLO-burn-rate load shedding through
``engine.health()`` and the obs ``on_shed`` hook, and graceful drain.
Every one of those mechanisms is host-side policy at the same
scheduler boundaries: the compiled quantum's ``max_host_callbacks=0``
budget and golden fingerprint are unchanged (the
``serving_frontdoor_step`` recipe pins the per-request-sampling
variant with its own golden).

TENSOR-PARALLEL SERVING (``mesh=`` / ``tp=``): the whole quantum
family — default greedy/sampling, the per-request-sampling front-door
variant, the speculative draft+verify round, and the mixed chunked-
prefill batches — runs head/ffn-sharded over a 1-axis ``("mp",)``
mesh. Params are re-placed at engine build with the same tp2 layouts
the training recipes pin (column: out-dim, row: in-dim, vocab-parallel
embedding), the paged pools go head-sharded (each chip holds every
block for ITS KV heads, so refcounted prefix sharing and COW stay pure
host bookkeeping), and each quantum remains ONE jitted dispatch whose
collectives GSPMD inserts in-graph — pools still donated, zero host
callbacks. The static collective profile (count/bytes by kind, read
from the compiled module at build) feeds the obs gauges and
``engine_stats()``; the ``serving_tp_step`` recipe pins the sharded
graph with ``min_sharded_params`` + a collective-byte cap and its own
golden. With no mesh (the default) every graph is byte-identical to
the single-chip engine — the tp parity tests exploit exactly that:
same seed, no mesh at model build, identical weights either way.
"""
from __future__ import annotations

import contextlib
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import autograd
from ..jit import functional_call
from ..nlp.generation import _filter_logits
from ..nlp.paged_cache import PagedKVCachePool
from ..nn.quant import quantize_for_serving, quantize_kv_rows
from ..obs.flight import FlightRecorder
from ..obs.serving import ServingObs
from ..obs.slo import SLOSet
from ..parallel import mesh as mesh_state
from ..parallel.mesh import MeshScope
from .faults import FaultInjector, InjectedFault
from .resilience import QuantumWatchdog, ResiliencePolicy
from .scheduler import Request, Scheduler, SchedulerConfig

__all__ = ["ServingEngine"]


def _resolve_tp_mesh(mesh, tp):
    """Normalize the engine's ``mesh=``/``tp=`` kwargs into
    ``(Mesh | None, tp_size)``. ``tp=1`` (or both None) is the
    single-chip engine — no mesh, byte-identical graphs. A bare ``tp=N``
    builds a 1-axis ``("mp",)`` mesh over the first N visible devices;
    an explicit mesh must carry an ``"mp"`` axis (and agree with ``tp``
    when both are given)."""
    if mesh is None and (tp is None or int(tp) <= 1):
        return None, 1
    from jax.sharding import Mesh

    if mesh is not None:
        if "mp" not in mesh.shape:
            raise ValueError(
                f"serving mesh has axes {tuple(mesh.shape)} but no 'mp' "
                f"axis: the quantum family shards params and KV pools "
                f"along 'mp' — build the mesh with an 'mp' axis (e.g. "
                f"Mesh(np.array(jax.devices()[:2]), ('mp',)))")
        size = int(mesh.shape["mp"])
        if tp is not None and int(tp) != size:
            raise ValueError(
                f"tp={tp} disagrees with the mesh's 'mp' axis size "
                f"{size}: pass only one, or make them match")
        return (mesh, size) if size > 1 else (None, 1)
    tp = int(tp)
    devs = jax.devices()
    if tp > len(devs):
        raise ValueError(
            f"tp={tp} needs {tp} visible devices but jax sees only "
            f"{len(devs)} ({devs[0].platform}). On CPU, expose virtual "
            f"devices BEFORE jax initializes — either "
            f"XLA_FLAGS='--xla_force_host_platform_device_count={tp}' "
            f"in the environment or "
            f"jax.config.update('jax_num_cpu_devices', {tp}) at startup "
            f"— then rebuild the engine")
    return Mesh(np.array(devs[:tp]), ("mp",)), tp


def _check_tp_divisible(cfg, tp, role):
    """The head-sharded layout needs both head counts to divide by tp:
    attention is computed per head, so a non-divisible count would force
    replicated attention and the pool could not shard at all."""
    if cfg.num_attention_heads % tp or cfg.num_key_value_heads % tp:
        raise ValueError(
            f"{role} model has num_attention_heads="
            f"{cfg.num_attention_heads}, num_key_value_heads="
            f"{cfg.num_key_value_heads}; both must divide by tp={tp} "
            f"for the head-sharded quantum layout")


def _tp_shard_params(model):
    """Re-place a tensor-parallel model's params onto the INSTALLED
    mesh (call under ``MeshScope``): mp-layer weights split along their
    parallel dim — the same tp2 layout the training recipes pin — and
    every other param committed replicated, so all quantum inputs are
    mesh-addressed. The model must have been BUILT with
    ``tensor_parallel=True`` but WITHOUT a mesh: mp layers then
    initialize exactly like their serial twins (same seed -> identical
    weights), which is what makes tp-vs-single-chip streams comparable
    bit-for-bit. Returns the number of mp-layer weights sharded (0
    means the model has no tensor-parallel structure)."""
    from ..distributed.fleet.layers.mpu.mp_layers import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
    from ..nn.quant import (
        QuantizedColumnParallelLinear, QuantizedRowParallelLinear)

    placed = set()

    def put(param, *spec):
        param._value = mesh_state.shard_value(param._value, *spec)
        placed.add(id(param))

    n_sharded = 0
    for _, layer in model.named_sublayers(include_self=True):
        if isinstance(layer, QuantizedColumnParallelLinear):
            # int8 weight splits like its float twin; the per-out-channel
            # scale vector rides the same "mp" split as the out dim.
            put(layer.quant_weight, None, "mp")
            put(layer.weight_scale, "mp")
            n_sharded += 1
            if layer.bias is not None:
                put(layer.bias, "mp")
        elif isinstance(layer, QuantizedRowParallelLinear):
            put(layer.quant_weight, "mp", None)
            put(layer.weight_scale)  # out-channel scales: replicated
            n_sharded += 1
            if layer.bias is not None:
                put(layer.bias)  # replicated: added after the all-reduce
        elif isinstance(layer, ColumnParallelLinear):
            put(layer.weight, None, "mp")
            n_sharded += 1
            if layer.bias is not None:
                put(layer.bias, "mp")
        elif isinstance(layer, RowParallelLinear):
            put(layer.weight, "mp", None)
            n_sharded += 1
            if layer.bias is not None:
                put(layer.bias)  # replicated: added after the all-reduce
        elif isinstance(layer, VocabParallelEmbedding):
            put(layer.weight, "mp", None)
            n_sharded += 1
    for _, p in model.named_parameters():
        if id(p) not in placed:
            p._value = mesh_state.replicate_value(p._value)
    return n_sharded


def _rope_rows(x, cos, sin):
    """Rotate (..., H, D) by per-row angles (..., D/2) — the model's
    default (neox) rotary layout at each row's own cache position.
    Broadcasts over any leading dims: (S, H, D) with (S, D/2) for the
    decode quantum, (S, C, H, D) with (S, C, D/2) for the speculative
    verify chunk."""
    xf = x.astype(jnp.float32)
    c = cos[..., None, :]
    s = sin[..., None, :]
    d = x.shape[-1]
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def _xla_paged_decode_attn(q, kp, vp, tables, lens, ks=None, vs=None):
    """Off-TPU decode attention over the paged pool: gather the table's
    blocks and run the same f32 masked softmax as the contiguous-cache
    fallback (`_masked_decode_attn`). ``ks``/``vs`` are the optional
    per-row scale pools of an int8 pool ((NB, BS, HK) f32): the gathered
    rows dequantize in f32 before the softmax, so the math matches the
    float path up to the quantization rounding itself."""
    s_, h, d = q.shape
    w = tables.shape[1]
    bs, hk = kp.shape[1], kp.shape[2]
    k = kp[tables].reshape(s_, w * bs, hk, d)
    v = vp[tables].reshape(s_, w * bs, hk, d)
    if ks is not None:
        k = k.astype(jnp.float32) * ks[tables].reshape(
            s_, w * bs, hk)[..., None]
        v = v.astype(jnp.float32) * vs[tables].reshape(
            s_, w * bs, hk)[..., None]
    rep = h // hk
    kr = jnp.repeat(k, rep, axis=2) if rep > 1 else k
    vr = jnp.repeat(v, rep, axis=2) if rep > 1 else v
    sc = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                        kr.astype(jnp.float32)) * sc
    mask = jnp.arange(w * bs)[None, :] < lens[:, None]
    logits = jnp.where(mask[:, None, :], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhk,bkhd->bhd", p, vr.astype(jnp.float32))
    return out.astype(q.dtype)


def _fused_paged_decode_attn(q, kp, vp, tables, lens, ks=None, vs=None):
    """Fused (flash-style) decode attention over the paged pool: an
    online-softmax scan over the BLOCK-TABLE entries, porting the two
    tricks the Pallas paged kernel and the d128 varlen retune already
    won (BENCH_NOTES "Paged KV-cache decode" / "flash/varlen kernel
    retune") to the portable XLA level:

      * no gathered copy — the oracle (`_xla_paged_decode_attn`)
        materializes the whole (S, W*BS, HK, D) context twice before a
        full-width softmax; here each scan step touches ONE pool block
        per row and folds it into running (m, l, acc) f32 statistics,
        so temp residency is per-block, not per-context.
      * DMA elision analog — a row whose context ended before block
        ``ki`` re-points its gather at pool block 0 (the Pallas
        kernel's clamped ``pool_idx`` map) and masks the whole block,
        so dead steps never touch cold pool memory.

    Same f32 compute dtype, same -1e30 mask, same trailing cast as the
    oracle; the online rescale chain reorders the softmax reductions,
    which is exactly why the gather path stays wired in as the parity
    oracle (streams compare bit-exact on the tiny recipe shapes — the
    bf16 output cast absorbs the ulp-level reassociation).
    ``ks``/``vs`` are the int8 pool's per-row scale pools: blocks
    dequantize in f32 as they stream through, never all at once."""
    s_, h, d = q.shape
    w = tables.shape[1]
    bs, hk = kp.shape[1], kp.shape[2]
    rep = h // hk
    sc = 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32)                        # (S, H, D)
    neg = jnp.float32(-1e30)

    def body(carry, ki):
        m, l, acc = carry
        start = ki * bs
        alive = start < lens                          # (S,)
        blk = jnp.where(alive, tables[:, ki], 0)      # elision clamp
        k = kp[blk].astype(jnp.float32)               # (S, BS, HK, D)
        v = vp[blk].astype(jnp.float32)
        if ks is not None:
            k = k * ks[blk][..., None]
            v = v * vs[blk][..., None]
        if rep > 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        logits = jnp.einsum("bhd,bkhd->bhk", qf, k) * sc   # (S, H, BS)
        mask = alive[:, None] & (
            (start + jnp.arange(bs))[None, :] < lens[:, None])
        logits = jnp.where(mask[:, None, :], logits, neg)
        m2 = jnp.maximum(m, jnp.max(logits, axis=-1))
        alpha = jnp.exp(m - m2)                       # (S, H)
        p = jnp.exp(logits - m2[..., None])           # (S, H, BS)
        l2 = l * alpha + jnp.sum(p, axis=-1)
        acc2 = acc * alpha[..., None] + jnp.einsum("bhk,bkhd->bhd", p, v)
        return (m2, l2, acc2), None

    m0 = jnp.full((s_, h), neg, jnp.float32)
    l0 = jnp.zeros((s_, h), jnp.float32)
    a0 = jnp.zeros((s_, h, d), jnp.float32)
    # every row attends >= 1 position (masked rows carry lens == 1), so
    # the first live block always lifts m above the -1e30 init before
    # any dead block's exp(neg - m) underflows to an exact 0
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, a0), jnp.arange(w))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _xla_paged_chunk_attn(q, kp, vp, tables, base_lens, ks=None, vs=None):
    """Chunked decode attention over the paged pool (the speculative
    VERIFY pass): query position j of each slot attends pool positions
    < base+j+1 — the same gather + f32 masked softmax as
    `_xla_paged_decode_attn` with an extra in-chunk causal dimension.
    q is (S, C, H, D); no Pallas analog yet, the gather fallback runs
    on every backend."""
    s_, c, h, d = q.shape
    w = tables.shape[1]
    bs, hk = kp.shape[1], kp.shape[2]
    k = kp[tables].reshape(s_, w * bs, hk, d)
    v = vp[tables].reshape(s_, w * bs, hk, d)
    if ks is not None:
        k = k.astype(jnp.float32) * ks[tables].reshape(
            s_, w * bs, hk)[..., None]
        v = v.astype(jnp.float32) * vs[tables].reshape(
            s_, w * bs, hk)[..., None]
    rep = h // hk
    kr = jnp.repeat(k, rep, axis=2) if rep > 1 else k
    vr = jnp.repeat(v, rep, axis=2) if rep > 1 else v
    sc = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bchd,bkhd->bhck", q.astype(jnp.float32),
                        kr.astype(jnp.float32)) * sc
    lens = base_lens[:, None] + jnp.arange(c)[None, :] + 1   # (S, C)
    mask = jnp.arange(w * bs)[None, None, :] < lens[:, :, None]
    logits = jnp.where(mask[:, None], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhck,bkhd->bchd", p, vr.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_attn(q, kp, vp, tables, lens, ks=None, vs=None,
                impl="gather"):
    """Route decode attention: Pallas paged kernel on TPU (block tables
    dereferenced in SMEM, one pool block DMA per grid step), XLA gather
    fallback elsewhere. Per-row scale pools (int8 engine) always take
    an XLA path: the Pallas kernel only supports STATIC per-head
    scales, not per-(block, position, head) pools. ``impl="fused"``
    selects the online-softmax block-streaming path
    (`_fused_paged_decode_attn`) for the XLA tier — the engine's
    ``attn_impl=`` knob; the default keeps every existing graph (and
    golden fingerprint) byte-identical."""
    from ..core.flags import get_flags

    if ks is None:
        flags = get_flags(
            ["FLAGS_use_pallas_kernels", "FLAGS_pallas_force"])
        use_pallas = flags["FLAGS_use_pallas_kernels"] and (
            jax.default_backend() == "tpu" or flags["FLAGS_pallas_force"])
        if use_pallas:
            from ..ops.pallas.paged_attention import paged_decode_attention

            return paged_decode_attention(q, kp, vp, tables, lens)
    if impl == "fused":
        return _fused_paged_decode_attn(q, kp, vp, tables, lens,
                                        ks=ks, vs=vs)
    return _xla_paged_decode_attn(q, kp, vp, tables, lens, ks=ks, vs=vs)


def _pin_kv(arr):
    """Constrain one per-layer pool array to the head-sharded mesh
    layout (``P(None, None, 'mp', None)``) so GSPMD keeps the donated
    pool outputs on exactly the layout they arrived in — the in-place
    block write must never force a gather/reshard of the whole pool.
    Identity when no mesh is installed, ``mp == 1``, or the KV-head dim
    doesn't divide: the single-chip quantum graphs (and their golden
    fingerprints) are untouched byte-for-byte."""
    mp = mesh_state.mesh_axis_size("mp")
    if mp > 1 and arr.shape[2] % mp == 0:
        return mesh_state.constraint(arr, None, None, "mp", None)
    return arr


def _pin_kv_scale(arr):
    """`_pin_kv` for the (NB, BS, HK) scale pools of an int8 pool: the
    kv-head axis is the last one, so the constraint drops the trailing
    head-dim entry. Same identity conditions as `_pin_kv`."""
    mp = mesh_state.mesh_axis_size("mp")
    if mp > 1 and arr.shape[2] % mp == 0:
        return mesh_state.constraint(arr, None, None, "mp")
    return arr


def paged_decode_math(model, scratch_block, ids_t, seq_lens, tables,
                      kc, vc, live, ks=(), vs=(), attn_impl="gather"):
    """One token for every slot over a paged pool (the quantum's
    per-step body; mirrors generation._manual_decode with block-table
    writes instead of dense-cache slice updates). Parameterized by
    ``model`` so the plain quantum (target) and the speculative DRAFT
    scan (serving/speculative.py) share one decode-step definition.

    ``ks``/``vs`` are the per-layer per-row scale pools of an int8
    pool (empty tuples on a float pool — zero extra avals, so the
    unquantized quantum graph and its golden are byte-identical): each
    KV row quantizes symmetrically at its write site and the gathered
    context dequantizes inside the attention math. Returns
    ``(logits, new_kc, new_vc, new_ks, new_vs)``; the scale tuples stay
    ``()`` when unquantized."""
    cfg = model.config
    core = model.llama
    s = ids_t.shape[0]
    h, hk, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                cfg.head_dim)
    bs = kc[0].shape[1]
    w = tables.shape[1]

    hidden = core.embed_tokens(ids_t)                # (S, 1, E)
    inv_freq = 1.0 / (cfg.rope_theta ** (
        jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    pos = seq_lens.astype(jnp.float32)
    freqs = pos[:, None] * inv_freq[None, :]
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)        # (S, D/2)

    blk_idx = jnp.clip(seq_lens // bs, 0, w - 1)
    own_blk = jnp.take_along_axis(tables, blk_idx[:, None],
                                  axis=1)[:, 0]
    write_blk = jnp.where(live, own_blk, scratch_block)
    write_off = jnp.where(live, seq_lens % bs, 0)
    lens = jnp.where(live, seq_lens + 1, 1)

    quant = len(ks) > 0
    new_kc, new_vc, new_ks, new_vs = [], [], [], []
    for i, layer in enumerate(core.layers):
        attn = layer.self_attn
        residual = hidden
        x = layer.input_layernorm(hidden)
        q = attn.q_proj(x).reshape([s, 1, h, d])
        k = attn.k_proj(x).reshape([s, 1, hk, d])
        v = attn.v_proj(x).reshape([s, 1, hk, d])
        qv = _rope_rows(q._value[:, 0], cos, sin)    # (S, H, D)
        kv = _rope_rows(k._value[:, 0], cos, sin)
        vv = v._value[:, 0]
        ksi = vsi = None
        if quant:
            kv, k_sc = quantize_kv_rows(kv)          # (S, HK, D)/(S, HK)
            vv, v_sc = quantize_kv_rows(vv)
            ksi = _pin_kv_scale(
                ks[i].at[write_blk, write_off].set(k_sc))
            vsi = _pin_kv_scale(
                vs[i].at[write_blk, write_off].set(v_sc))
            new_ks.append(ksi)
            new_vs.append(vsi)
        kci = _pin_kv(kc[i].at[write_blk, write_off].set(
            kv.astype(kc[i].dtype)))
        vci = _pin_kv(vc[i].at[write_blk, write_off].set(
            vv.astype(vc[i].dtype)))
        new_kc.append(kci)
        new_vc.append(vci)
        att = _paged_attn(qv, kci, vci, tables, lens, ks=ksi, vs=vsi,
                          impl=attn_impl)
        att_t = Tensor(att.reshape(s, 1, h * d), stop_gradient=True)
        hidden = residual + attn.o_proj(att_t)
        hidden = hidden + layer.mlp(
            layer.post_attention_layernorm(hidden))
    hidden = core.norm(hidden)
    logits = model.lm_head(hidden)
    return (logits._value[:, 0], new_kc, new_vc,
            tuple(new_ks), tuple(new_vs))


def paged_chunk_math(model, scratch_block, ids_t, seq_lens, tables,
                     kc, vc, live, ks=(), vs=()):
    """C-token suffix forward for every slot over a paged pool — the
    speculative round's TARGET verify pass (reference: the speculative
    verify forward of the reference's serving stack — unverified,
    SURVEY.md §0). Chunk position j writes its KV at ``seq_lens + j``
    (masked rows go to the scratch block) and attends its own prefix;
    one batched forward covers all slots and all γ+1 positions. Stale
    tail slots from rejected proposals are rolled back by LENGTH MASK:
    the caller shrinks ``seq_lens`` and the next round's writes simply
    overwrite them."""
    cfg = model.config
    core = model.llama
    s, c = ids_t.shape
    h, hk, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                cfg.head_dim)
    bs = kc[0].shape[1]
    w = tables.shape[1]

    hidden = core.embed_tokens(ids_t)                # (S, C, E)
    inv_freq = 1.0 / (cfg.rope_theta ** (
        jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    pos_f = (seq_lens[:, None]
             + jnp.arange(c)[None, :]).astype(jnp.float32)
    freqs = pos_f[..., None] * inv_freq              # (S, C, D/2)
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)

    wpos = seq_lens[:, None] + jnp.arange(c)[None, :]
    blk_idx = jnp.clip(wpos // bs, 0, w - 1)
    own_blk = jnp.take_along_axis(tables, blk_idx, axis=1)
    write_blk = jnp.where(live[:, None], own_blk, scratch_block)
    write_off = jnp.where(live[:, None], wpos % bs, 0)
    base_lens = jnp.where(live, seq_lens, 0)

    quant = len(ks) > 0
    new_kc, new_vc, new_ks, new_vs = [], [], [], []
    for i, layer in enumerate(core.layers):
        attn = layer.self_attn
        residual = hidden
        x = layer.input_layernorm(hidden)
        q = attn.q_proj(x).reshape([s, c, h, d])
        k = attn.k_proj(x).reshape([s, c, hk, d])
        v = attn.v_proj(x).reshape([s, c, hk, d])
        qv = _rope_rows(q._value, cos, sin)          # (S, C, H, D)
        kv = _rope_rows(k._value, cos, sin)
        vv = v._value
        ksi = vsi = None
        if quant:
            kv, k_sc = quantize_kv_rows(kv)      # (S,C,HK,D)/(S,C,HK)
            vv, v_sc = quantize_kv_rows(vv)
            ksi = _pin_kv_scale(
                ks[i].at[write_blk, write_off].set(k_sc))
            vsi = _pin_kv_scale(
                vs[i].at[write_blk, write_off].set(v_sc))
            new_ks.append(ksi)
            new_vs.append(vsi)
        kci = _pin_kv(kc[i].at[write_blk, write_off].set(
            kv.astype(kc[i].dtype)))
        vci = _pin_kv(vc[i].at[write_blk, write_off].set(
            vv.astype(vc[i].dtype)))
        new_kc.append(kci)
        new_vc.append(vci)
        att = _xla_paged_chunk_attn(qv, kci, vci, tables, base_lens,
                                    ks=ksi, vs=vsi)
        att_t = Tensor(att.reshape(s, c, h * d), stop_gradient=True)
        hidden = residual + attn.o_proj(att_t)
        hidden = hidden + layer.mlp(
            layer.post_attention_layernorm(hidden))
    hidden = core.norm(hidden)
    logits = model.lm_head(hidden)
    return logits._value, new_kc, new_vc, tuple(new_ks), tuple(new_vs)


class _AuditedStep:
    """Callable+lowerable wrapper handed to ``analysis.check_budget``:
    declares how many LEADING flat args the quantum donates (the KV
    pool leaves — 2L for the plain quantum, 2L_target + 2L_draft for
    the speculative round) so ``require_donated`` audits the right
    set. A TP engine also carries its mesh: the audit re-traces the
    quantum OUTSIDE the engine's dispatch path, so trace and lowering
    here must run under the same ``MeshScope`` the engine uses (mp
    layers degrade to serial math when no mesh is installed)."""

    def __init__(self, jitted, n_donatable, name="serving_decode_quantum",
                 mesh=None):
        self._jitted = jitted
        self.n_donatable = int(n_donatable)
        self.__name__ = name
        self._mesh = mesh

    def _scope(self):
        return (MeshScope(self._mesh) if self._mesh is not None
                else contextlib.nullcontext())

    def __call__(self, *args):
        with self._scope():
            return self._jitted(*args)

    def lower(self, *args):
        with self._scope():
            return self._jitted.lower(*args)


class ServingEngine:
    """Multiplex many in-flight generation requests over one shared
    paged KV pool and one jitted decode step.

    Args:
        model: a LlamaForCausalLM-shaped causal LM (eval mode; params
            define the cache dtype).
        num_slots: fixed decode batch capacity (padded active set).
        block_size: KV pool block size in tokens.
        num_blocks: pool capacity; default sizes the pool for
            ``num_slots`` full-context sequences plus the scratch block.
        max_context: per-request prompt+generation bound (defaults to
            the model's max_position_embeddings).
        prefill_chunk / decode_quantum: see SchedulerConfig.
        decode_strategy: "greedy" | "sampling" (engine-wide; sampling
            knobs via top_k/top_p/temperature, per-request seeds).
        eos_token_id: retire a slot the step after it emits this id.
        spec_draft: optional DRAFT causal LM (same vocab) switching the
            decode quantum to the speculative drafter/verifier round
            (serving/speculative.py): the draft scans ``spec_gamma``
            proposals, the target verifies all γ+1 positions in one
            forward, and acceptance/bonus/resample + both caches' roll
            forward/back happen in-graph — ONE dispatch per round. The
            greedy arm emits exactly the target's greedy stream; the
            sampling arm is distribution-exact rejection sampling.
        spec_gamma: proposals per speculative round (default 4).
        prefix_cache: enable CONTENT-ADDRESSED PREFIX CACHING
            (default OFF this release): full prompt blocks are
            published into the pool's chain-hash index at prefill
            completion, and admission aliases the longest cached chain
            into the new request's block tables (target + draft pool in
            lockstep) — prefill then skips the aliased tokens, so
            prefill compute and novel pool residency scale with UNIQUE
            tokens (the shared-system-prompt TTFT win). The first
            token written into a still-shared block copy-on-writes it;
            eviction under pool pressure reclaims cached blocks only
            at refcount one. All of it is host-side allocator policy:
            the compiled quantum, its golden fingerprint, and the
            emitted streams are bit-identical either way (the
            ``serving_prefix_step`` recipe gates this).
        per_request_sampling: build the FRONT-DOOR quantum variant
            (requires ``decode_strategy="sampling"``): each slot's
            temperature rides the per-slot state as one extra (S,)
            f32 quantum input, so ``submit(..., temperature=)`` works
            per request. The default engine's quantum signature — and
            its golden fingerprint — are untouched; the variant is
            pinned by its own ``serving_frontdoor_step`` recipe.
        obs: observability sink — ``None`` builds a fresh
            :class:`~paddle_tpu.obs.serving.ServingObs` (metrics
            registry always on), ``"off"`` disables the rich hooks
            (histograms/gauges/tracer; the legacy ``stats`` counters
            keep working — the overhead-bench baseline), or pass a
            :class:`ServingObs` to share a registry across engines.
            Every hook fires at host scheduler boundaries only: the
            jitted quantum keeps its ``max_host_callbacks=0`` budget
            and byte-identical golden fingerprint (tier-1 gated).
        trace: record Chrome trace events (request lifecycle spans,
            quantum spans, occupancy/pool counter tracks) into
            ``engine.obs.tracer`` — export with
            ``engine.obs.tracer.save(path)``, open in Perfetto.
        slo: serving objectives (:mod:`paddle_tpu.obs.slo`) —
            ``True`` attaches the stock set (p95 TTFT, p99 inter-token,
            p99 e2e, error/shed rate), or pass an
            :class:`~paddle_tpu.obs.slo.SLOSet` / list of
            :class:`~paddle_tpu.obs.slo.SLO`. ``engine.health()``
            evaluates them with multi-window burn rates over the obs
            sample series; the exporter's ``/healthz`` & ``/slo``
            endpoints (obs/export.py) serve the same report live.
        flight: per-request flight recorder
            (:mod:`paddle_tpu.obs.flight`) — ``True`` builds one whose
            dump-on-anomaly thresholds come from ``slo``, or pass a
            :class:`~paddle_tpu.obs.flight.FlightRecorder`. Journals
            every lifecycle event (submit/admit/prefill chunks/first
            token/quantum yields/spec rounds/retire) at host scheduler
            boundaries; a request crossing its TTFT/e2e SLO threshold
            dumps its full journal to ``engine.flight.anomalies``.
            Like every obs hook, the compiled quantum is untouched
            (fingerprint-gated).
        mesh / tp: TENSOR-PARALLEL SERVING. ``tp=N`` (N > 1) builds a
            1-axis ``("mp",)`` mesh over the first N visible devices;
            ``mesh=`` passes an explicit ``jax.sharding.Mesh`` with an
            ``"mp"`` axis instead (both together must agree). The model
            (and draft) must be BUILT with ``tensor_parallel=True`` but
            WITHOUT a global mesh — mp layers then initialize exactly
            like their serial twins, so a tp engine and a single-chip
            engine seeded identically hold identical weights and their
            streams compare bit-for-bit (the tier-1 parity oracle). At
            engine build the params are re-placed head/ffn-sharded
            (Column/Row-parallel + vocab-parallel layouts, the same tp2
            placement the training recipes pin), the paged KV pools go
            head-sharded (``P(None, None, 'mp', None)`` — block ids and
            refcounted prefix sharing/COW stay plain host bookkeeping),
            and every quantum variant remains ONE jitted dispatch with
            in-graph collectives, pools still donated. The quantum's
            static collective profile (count + bytes by kind, from the
            compiled module at build — never runtime callbacks) lands
            in ``engine_stats()['quantum_collectives']`` and the obs
            registry. Default ``tp=None`` (single chip): no mesh, and
            every compiled graph — and golden fingerprint — is
            byte-identical to previous releases. On CPU expose virtual
            devices BEFORE jax initializes (e.g.
            ``XLA_FLAGS='--xla_force_host_platform_device_count=8'``).
        faults: a :class:`~paddle_tpu.serving.faults.FaultInjector`
            threaded through the engine's host boundaries (quantum
            dispatch, pool allocation, cached-KV corruption). Default:
            a fresh DISARMED injector — every hook is a constant-time
            no-op and all compiled goldens stay byte-identical (the
            serving recipes build with exactly this to pin it).
        resilience: ``True`` (stock
            :class:`~paddle_tpu.serving.resilience.ResiliencePolicy`)
            or a policy instance arms the resilience tier: injected
            faults retry with exponential backoff then contain at the
            step boundary (poison requests are isolated by batch
            bisect and finished with ``finish_reason="error"``), a
            wall-clock watchdog self-calibrated from the quantum
            latency histogram feeds the degradation ladders (repeated
            spec-round faults fall back to the plain quantum — same
            compiled family, no new golden), prefix chain-hash content
            verify quarantines corrupted cached subtrees, and pool
            accounting drift rebuilds the allocator from the live
            block tables. Default ``None``: fail-stop exactly as
            before.
        quantize: ``"weight_only_int8"`` (or ``"llm.int8"``) sweeps the
            target — and draft — stacks through
            :func:`~paddle_tpu.nn.quant.quantize_for_serving` at build,
            BEFORE AOT lowering: every quantum arm's executable carries
            int8 weights + per-out-channel scales, and the dequant
            multiply fuses into each matmul (weights stay int8 in HBM).
            The per-element dequant is IEEE-exact, so greedy streams are
            BIT-IDENTICAL to a float engine holding the dequantized
            weights — the parity oracle the tests pin. TP-composable:
            quantized mp layers shard their scales with the layer's
            split. Default ``None``: float weights, graphs untouched.
        kv_dtype: ``"int8"`` builds both paged pools quantized: int8
            block buffers plus per-row f32 scale pools ((NB, BS, HK),
            one scale per written row), symmetric abs-max quant at
            every KV-write site IN-GRAPH and dequant inside the
            attention gather — still one dispatch, all four pool
            pytrees donated. A row's scale depends only on its own
            values, so prefix sharing, COW (scale rows copy with the
            block), LRU eviction, preemption, and snapshot/restore work
            unchanged, and shared-vs-unshared streams stay
            bit-identical. Halves KV residency (int8 + d-wide scale vs
            2-byte floats). Default ``None``: float pools, every
            existing golden byte-identical (the scale tuples are empty
            pytrees — zero extra avals in the quantum signature).
        cost_model: ``True`` sizes the cost ledger's MFU numerator from
            the static cost model (:mod:`paddle_tpu.analysis.cost`):
            the decode quantum's jaxpr-walked FLOPs per token — which
            counts attention over live context and the lm-head that
            the ``2N`` weight-matmul floor deliberately excludes —
            clamped to never fall below that floor. Host-side
            accounting only; the compiled quantum and its golden are
            untouched. Default ``False``: the 2N floor, as before.
        multi_quantum: MULTI-QUANTUM DECODE DRIVER. ``K > 1`` builds a
            second quantum-family variant that runs UP TO K decode
            quanta per dispatch under ``lax.while_loop``, re-entering
            the host only when the scheduler's ``steady_state()``
            predicate says admission could change (waiting queue
            non-empty, a slot mid-prefill) or every row retired — the
            on-device eos/max-len masks the quantum already carries
            both retire rows mid-flight AND short-circuit the loop when
            the whole batch is done. The driver accounts a K-quantum
            dispatch as K quanta (obs histograms, cost ledger, flight
            journals, watchdog normalization), so every conservation
            invariant holds exactly, and its streams are BIT-IDENTICAL
            to the per-quantum driver: between steady-state quanta the
            host round-trips device state through int32 mirrors without
            touching it, so folding K round-trips into the device loop
            changes no math (tests pin greedy/sampling/prefix/int8/
            preemption arms). Admission reservations already cover each
            row's worst-case growth (``prompt + max_new + margin``), so
            the K-wide block-table pre-growth can never oversubscribe
            the pool. A speculative engine ignores K: each spec round
            needs its acceptance counts on the host. Default ``1``: the
            variant isn't built, nothing changes.
        attn_impl: ``"fused"`` switches the decode quantum's inner loop
            to the online-softmax block-streaming attention
            (`_fused_paged_decode_attn` — flash-style m/l/acc over
            block-table entries, no (S, W*BS, HK, D) gathered copy,
            dead blocks clamped to pool block 0), the XLA-level port of
            the Pallas paged kernel's DMA-elision trick. The gather
            path stays the parity oracle; the ``serving_multiquantum_
            step`` recipe pins the fused graph's own golden. Default
            ``"gather"``: every existing graph byte-identical.
    """

    def __init__(self, model, num_slots=8, block_size=32, num_blocks=None,
                 max_context=None, prefill_chunk=64, decode_quantum=8,
                 decode_strategy="greedy", top_k=0, top_p=1.0,
                 temperature=1.0, eos_token_id=None, spec_draft=None,
                 spec_gamma=4, prefix_cache=False,
                 per_request_sampling=False, obs=None,
                 trace=False, slo=None, flight=None, mesh=None, tp=None,
                 faults=None, resilience=None, quantize=None,
                 kv_dtype=None, cost_model=False, multi_quantum=1,
                 attn_impl="gather"):
        cfg = model.config
        if getattr(cfg, "sliding_window", None):
            raise NotImplementedError(
                "ServingEngine does not compose with sliding_window: a "
                "rolling buffer wrap-writes over pool slots the block "
                "tables still map")
        if decode_strategy not in ("greedy", "sampling"):
            raise ValueError(
                f"decode_strategy must be greedy|sampling, got "
                f"{decode_strategy!r}")
        self._per_request_sampling = bool(per_request_sampling)
        if self._per_request_sampling and decode_strategy != "sampling":
            raise ValueError(
                "per_request_sampling=True requires "
                "decode_strategy='sampling' (per-slot temperature only "
                "changes the sampling quantum)")
        if self._per_request_sampling and spec_draft is not None:
            raise NotImplementedError(
                "per_request_sampling does not compose with spec_draft "
                "yet: the speculative round's acceptance math takes the "
                "engine-wide temperature")
        if attn_impl not in ("gather", "fused"):
            raise ValueError(
                f"attn_impl must be gather|fused, got {attn_impl!r}")
        self.attn_impl = attn_impl
        self._mq_max = int(multi_quantum)
        if self._mq_max < 1:
            raise ValueError(
                f"multi_quantum must be >= 1, got {multi_quantum}")
        self.mesh, self.tp = _resolve_tp_mesh(mesh, tp)
        if self.tp > 1:
            _check_tp_divisible(cfg, self.tp, "target")
            if spec_draft is not None:
                _check_tp_divisible(spec_draft.config, self.tp, "draft")
        if spec_draft is not None:
            d_cfg = spec_draft.config
            if getattr(d_cfg, "sliding_window", None):
                raise NotImplementedError(
                    "speculative serving with a sliding-window draft is "
                    "not supported: rollback-by-length-mask cannot "
                    "restore rolling-buffer slots rejected proposals "
                    "wrapped over")
            if d_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {d_cfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size}: acceptance compares token ids")
            if int(spec_gamma) < 1:
                raise ValueError(
                    f"spec_gamma must be >= 1, got {spec_gamma}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"unsupported kv_dtype {kv_dtype!r} (None or 'int8')")
        self.quantize = quantize
        self.kv_dtype = kv_dtype
        self.model = model
        if quantize is not None:
            # sweep BEFORE .eval()/tp-shard/_p_vals snapshot: the
            # quantized params must be what every arm lowers against
            quantize_for_serving(model, algo=quantize)
            if spec_draft is not None:
                quantize_for_serving(spec_draft, algo=quantize)
        model.eval()
        self.spec_draft = spec_draft
        self.spec_gamma = int(spec_gamma)
        self.config = SchedulerConfig(num_slots=num_slots,
                                      prefill_chunk=prefill_chunk,
                                      decode_quantum=decode_quantum)
        self.decode_strategy = decode_strategy
        self.top_k = 0 if top_k is None else int(top_k)
        self.top_p = 1.0 if top_p is None else float(top_p)
        self.temperature = 1.0 if temperature is None else float(temperature)
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))

        self.max_context = int(max_context
                               or cfg.max_position_embeddings)
        if self.tp > 1:
            with MeshScope(self.mesh):
                if _tp_shard_params(model) == 0:
                    raise ValueError(
                        "tp>1 needs a tensor-parallel model: build it "
                        "with config.tensor_parallel=True (Column/Row-"
                        "parallel layers) — this model has no mp layers "
                        "to shard")
        self._p_vals = [p._value for _, p in model.named_parameters()]
        # the model dtype the float pools inherit: first FLOATING param
        # (a quantized stack's first param may be an int8 weight)
        cache_dtype = next(
            (v.dtype for v in self._p_vals
             if jnp.issubdtype(v.dtype, jnp.floating)),
            self._p_vals[0].dtype)
        s = self.config.num_slots
        bs = int(block_size)
        # the speculative verify writes up to gamma slots past the
        # accepted history before the length mask rolls them back, so
        # tables (and the worst-case admission demand) carry that margin
        margin = self.spec_gamma if spec_draft is not None else 0
        w = -(-(self.max_context + margin) // bs)
        if num_blocks is None:
            num_blocks = s * w + 1  # +1: the masked-write scratch block
        self.prefix_cache = bool(prefix_cache)
        self.pool = PagedKVCachePool(
            num_blocks, bs, cfg.num_key_value_heads, cfg.head_dim,
            num_layers=cfg.num_hidden_layers, dtype=cache_dtype,
            prefix_cache=self.prefix_cache, mesh=self.mesh,
            kv_dtype=kv_dtype)
        # masked (retired/empty) rows dump their KV writes here
        self._scratch_block = self.pool.ensure("__scratch__", 1)[0]
        self.d_pool = None
        if spec_draft is not None:
            spec_draft.eval()
            if self.tp > 1:
                with MeshScope(self.mesh):
                    if _tp_shard_params(spec_draft) == 0:
                        raise ValueError(
                            "tp>1 needs a tensor-parallel DRAFT model: "
                            "build it with config.tensor_parallel=True "
                            "— this draft has no mp layers to shard")
            self._d_p_vals = [p._value
                              for _, p in spec_draft.named_parameters()]
            d_cfg = spec_draft.config
            d_cache_dtype = next(
                (v.dtype for v in self._d_p_vals
                 if jnp.issubdtype(v.dtype, jnp.floating)),
                self._d_p_vals[0].dtype)
            # the draft pool quantizes too: spec decoding doubles pool
            # pressure, so the residency win must cover both pools
            self.d_pool = PagedKVCachePool(
                num_blocks, bs, d_cfg.num_key_value_heads,
                d_cfg.head_dim, num_layers=d_cfg.num_hidden_layers,
                dtype=d_cache_dtype,
                prefix_cache=self.prefix_cache, mesh=self.mesh,
                kv_dtype=kv_dtype)
            self._d_scratch_block = self.d_pool.ensure("__scratch__",
                                                       1)[0]
        self.scheduler = Scheduler(
            self.config, self.pool, reserved_blocks=1,
            companion_pools=[self.d_pool] if self.d_pool is not None
            else [], token_margin=margin)
        self._table_width = w

        # host mirrors of the per-slot device state
        self._tables = np.zeros((s, w), np.int32)
        self._seq_lens = np.zeros(s, np.int32)
        self._last_tok = np.zeros(s, np.int32)
        self._n_gen = np.zeros(s, np.int32)
        self._done = np.ones(s, bool)
        self._max_new = np.zeros(s, np.int32)
        self._keys = np.zeros((s, 2), np.uint32)
        # per-slot temperature: an input of the front-door quantum
        # variant (per_request_sampling=True); the default engine's
        # quantum signature — and golden fingerprint — never sees it
        self._temps = np.ones(s, np.float32)
        # front-door streaming hook: called (req, token) for EVERY
        # token appended to a request's stream, at the same host
        # boundary obs.on_token fires on
        self.token_sink = None

        # rotary table shared by prefill (block_mha fused rope) and the
        # quantum (per-row angles recomputed on device)
        from ..nn.functional.rope import build_rope_cache

        cos, sin = build_rope_cache(self.max_context, cfg.head_dim,
                                    base=cfg.rope_theta)
        self._rotary = Tensor(jnp.stack([cos, sin]), stop_gradient=True)

        if spec_draft is not None:
            from .speculative import make_spec_round

            self._d_tables = np.zeros((s, w), np.int32)
            d_cos, d_sin = build_rope_cache(
                self.max_context, d_cfg.head_dim,
                base=d_cfg.rope_theta)
            self._d_rotary = Tensor(jnp.stack([d_cos, d_sin]),
                                    stop_gradient=True)
            # argnums 0..7 = target kc/vc/ks/vs + draft kc/vc/ks/vs; on
            # a float engine the scale tuples are EMPTY pytrees, so
            # donating them is a no-op and the flat donated set — and
            # every existing golden — is unchanged
            self._quantum = jax.jit(
                make_spec_round(self),
                donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
            self._audited = _AuditedStep(
                self._quantum,
                n_donatable=(4 if self.pool.quantized else 2)
                * (cfg.num_hidden_layers + d_cfg.num_hidden_layers),
                name="speculative_verify_step", mesh=self.mesh)
        else:
            self._quantum = jax.jit(self._make_quantum(),
                                    donate_argnums=(0, 1, 2, 3))
            self._audited = _AuditedStep(
                self._quantum,
                n_donatable=(4 if self.pool.quantized else 2)
                * cfg.num_hidden_layers,
                mesh=self.mesh)
        # the multi-quantum while_loop variant: built ONLY when asked
        # for (K > 1, non-speculative) — same signature as the plain
        # quantum, so `_quantum_args()` feeds both; the default
        # engine's compiled family and goldens never see it
        self._mq_quantum = None
        self._mq_audited = None
        if self._mq_max > 1 and spec_draft is None:
            self._mq_quantum = jax.jit(
                self._make_quantum(multi=self._mq_max),
                donate_argnums=(0, 1, 2, 3))
            self._mq_audited = _AuditedStep(
                self._mq_quantum,
                n_donatable=(4 if self.pool.quantized else 2)
                * cfg.num_hidden_layers,
                name="serving_multiquantum_step", mesh=self.mesh)
        # under tp the small per-slot state rides every dispatch
        # committed replicated, so the compiled quantum's input layouts
        # are pinned (never re-inferred per call)
        self._rep_sharding = None
        if self.tp > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            self._rep_sharding = NamedSharding(self.mesh,
                                               PartitionSpec())
        # build-time collective census (tp > 1 only): lower + compile
        # the quantum ONCE under the mesh, census the post-GSPMD module
        # for the obs gauges, and KEEP the compiled executable as the
        # dispatch target — the census compile IS the engine's compile,
        # so the profile costs no extra compile and needs no runtime
        # callbacks. tp=1 engines honestly report zeros (their recipes
        # already pin max_total_collectives=0).
        self._quantum_compiled = None
        self.quantum_collectives = {"tp": self.tp, "count_total": 0,
                                    "bytes_total": 0, "by_kind": {}}
        if self.tp > 1:
            from ..analysis.collectives import collective_census

            with MeshScope(self.mesh):
                self._quantum_compiled = self._quantum.lower(
                    *self._quantum_args()).compile()
            census = collective_census(self._quantum_compiled.as_text())
            by_kind = {k: {"count": st.count, "bytes": st.bytes}
                       for k, st in census.items() if st.count}
            self.quantum_collectives = {
                "tp": self.tp,
                "count_total": sum(d["count"] for d in by_kind.values()),
                "bytes_total": sum(d["bytes"] for d in by_kind.values()),
                "by_kind": by_kind,
            }
        self.completed: list = []
        # observability: metrics registry (always on unless "off") +
        # optional tracer; `stats` is the legacy dict READ/WRITE view
        # over the same registry counters (one source of truth)
        if obs == "off":
            self.obs = ServingObs(enabled=False)
        elif obs is None:
            self.obs = ServingObs(trace=trace)
        else:
            self.obs = obs
            if trace and self.obs.tracer is None:
                from ..obs.trace import TraceRecorder

                self.obs.tracer = TraceRecorder()
        self._now = self.obs.now
        self.stats = self.obs.legacy_stats_view()
        # static per-build collective profile -> registry gauges (zeros
        # suppressed; a tp=1 engine leaves the series empty)
        self.obs.set_quantum_collectives(self.quantum_collectives)
        # cost-ledger MFU constants (obs/attribution.py): target-model
        # FLOPs per decoded token (2N weight-matmul floor, embedding
        # gathers excluded) and the chip peak (0.0 off TPU — the MFU
        # gauge then honestly reads 0 and raw FLOP/s is the number)
        from ..obs.attribution import decode_flops_per_token
        from ..profiler.mfu import peak_flops_per_chip

        n_params = sum(int(v.size) for v in self._p_vals)
        embed = (int(getattr(cfg, "vocab_size", 0))
                 * int(getattr(cfg, "hidden_size", 0)))
        # int8 flops model: a quantized stack feeds the MXU's int8 path,
        # whose peak is 2x the bf16 peak — the MFU denominator doubles
        # (flops per token is unchanged: same 2N contraction count)
        flops_tok = decode_flops_per_token(
            n_params, n_embedding_params=embed)
        if cost_model:
            # opt-in: count the ACTUAL decode quantum's jaxpr (attention
            # over live context + lm-head, which 2N excludes) and take
            # the larger — the walker returns 0.0 when the quantum
            # cannot be traced, so the floor always survives
            try:
                from ..analysis.cost import quantum_flops_per_token

                flops_tok = max(quantum_flops_per_token(self), flops_tok)
            except Exception:
                pass
        self.obs.ledger.configure(
            flops_per_token=flops_tok,
            peak_flops=peak_flops_per_chip()
            * (2.0 if quantize is not None else 1.0))
        # SLO + flight recorder (the operability tier over the obs
        # boundaries): health feeds the front door's shedding policy
        # (serving/frontend.py), and the journal explains a slow tail
        # request after the fact
        if slo is True:
            self.slo = SLOSet()
        elif slo is None or isinstance(slo, SLOSet):
            self.slo = slo
        else:
            self.slo = SLOSet(slo)
        if flight is True:
            self.flight = FlightRecorder(slo=self.slo)
        elif flight is None or flight is False:
            self.flight = None
        else:
            self.flight = flight
        # resilience tier (serving/faults.py + serving/resilience.py):
        # a disarmed injector is a constant-time no-op at every hook,
        # so a default engine — and every golden — is untouched
        self.faults = faults if faults is not None else FaultInjector()
        self.pool.fault_hook = self.faults.on_alloc
        if self.d_pool is not None:
            self.d_pool.fault_hook = self.faults.on_alloc
        if resilience is True:
            resilience = ResiliencePolicy()
        self.resilience = resilience
        self.watchdog = (QuantumWatchdog(resilience)
                         if resilience is not None else None)
        if resilience is not None and self.prefix_cache:
            # arm the chain-hash content verify: publish records a
            # per-block checksum, attach re-verifies before aliasing
            self.pool.kv_checksums = True
            if self.d_pool is not None:
                self.d_pool.kv_checksums = True
        self._spec_disabled = False
        self._plain_quantum = None
        self._plain_audited = None
        self._spec_faults = 0
        self._isolating = False
        self._quarantined = []   # req_ids finished with reason "error"
        self._pool_rebuilds = 0
        self._step_skips = 0
        self._retries_total = 0
        self._fault_mark = 0     # injector-journal cursor -> obs/flight
        self._prefix_quarantine_mark = 0

    # -- public API --------------------------------------------------------
    def submit(self, prompt, max_new_tokens=32, req_id=None, seed=0,
               arrival_time=None, priority=1, temperature=None,
               stop_token_ids=None, stop_sequences=None):
        """Queue one request; returns the :class:`Request` handle.

        Per-request knobs: ``priority`` (admission class, see
        serving/policy.py), ``temperature`` (needs an engine built with
        ``per_request_sampling=True``), ``stop_token_ids`` /
        ``stop_sequences`` (host-side stop rules; ``finish_reason``
        becomes ``"stop"``), plus the existing ``max_new_tokens`` /
        ``seed``."""
        if temperature is not None and not self._per_request_sampling:
            raise ValueError(
                "per-request temperature needs an engine built with "
                "per_request_sampling=True (and "
                "decode_strategy='sampling')")
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      req_id=req_id, seed=seed, priority=priority,
                      temperature=temperature,
                      stop_token_ids=stop_token_ids,
                      stop_sequences=stop_sequences,
                      arrival_time=(self._now()
                                    if arrival_time is None
                                    else arrival_time))
        total = req.prompt_len + req.max_new_tokens
        if total > self.max_context:
            raise ValueError(
                f"request needs {total} tokens > max_context "
                f"{self.max_context}")
        self.scheduler.submit(req)
        self._on_submitted(req)
        return req

    def preempt(self, req):
        """Evict a live request under pool pressure: its blocks return
        to every pool (refcount-safe), its slot frees, and it re-enters
        the head of its priority class for recompute-on-resume — the
        next admission re-prefills ``prompt + tokens`` and the stream
        continues bit-exact vs an undisturbed run (tests/test_serving's
        preemption oracle). The evicted KV (``seq_lens[slot]`` cached
        tokens) is counted as recompute debt."""
        if req.slot is None or req.finished:
            raise ValueError(
                f"request {req.req_id} is not live — only an admitted, "
                f"unfinished request can be preempted")
        slot = req.slot
        now = self._now()
        cached = int(self._seq_lens[slot])
        self._done[slot] = True
        self._max_new[slot] = 0
        self.scheduler.preempt(req)
        self.obs.on_preempt(req, now, cached_tokens=cached)
        if self.flight is not None:
            self.flight.on_preempt(req, now, cached_tokens=cached,
                                   tokens_emitted=len(req.tokens))
        return req

    def _on_submitted(self, req):
        """Observability fan-out for one queued request (req_id is
        assigned by the scheduler, so this runs after its submit)."""
        self.obs.on_submit(req)
        if self.flight is not None:
            self.flight.on_submit(req, req.arrival_time)

    @property
    def has_work(self):
        return self.scheduler.has_work

    def step(self):
        """One scheduler iteration: admit, then either a mixed
        prefill(+decode) step or a jitted decode quantum, then retire.

        With ``resilience=`` the step is also the FAULT BOUNDARY: pool
        accounting is audited first (drift rebuilds the allocator from
        the live block tables instead of killing the engine), and an
        :class:`~paddle_tpu.serving.faults.InjectedFault` that survives
        the retry budget is contained here — a poison request is
        isolated by batch bisect and finished with
        ``finish_reason="error"``; a transient fault skips the step
        (nothing was dispatched, so the next step simply retries)."""
        return self.step_collect(self.step_dispatch())

    def step_dispatch(self):
        """DISPATCH HALF of :meth:`step` — admit, then enqueue the
        decode quantum WITHOUT forcing its results, returning an opaque
        pending record for :meth:`step_collect` (or ``None`` when the
        step completed synchronously: mixed prefill steps, speculative
        rounds, fault-contained steps, and idle engines). JAX dispatch
        is async, so between the two halves the device executes while
        the host is free to run OTHER work — the cluster front door
        dispatches every replica before collecting any, and a single
        engine's ``step()`` is exactly ``step_collect(step_dispatch())``
        (same ordering, same fault boundaries, bit-identical streams)."""
        self.stats["steps"] += 1
        if self.resilience is not None:
            self._audit_pools()
        if self.faults.armed:
            self.faults.maybe_corrupt(self.pool)
        pending = None
        try:
            self._admit()
            live = self.scheduler.live()
            self.stats["occupancy_sum"] += (
                len(live) / self.config.num_slots)
            self.obs.on_step(self._now(), len(live),
                             self.config.num_slots, self.pool,
                             self.d_pool)
            if self.scheduler.prefilling():
                self._mixed_step()
            elif self.scheduler.decoding():
                pending = self._decode_dispatch()
        except InjectedFault as e:
            self._contain_fault(e)
        finally:
            if pending is None:
                # the step ran to completion (or contained a fault)
                # inside this half — close the fault boundary here
                self._sync_faults()
                self._sync_prefix_quarantines()
        return pending

    def step_collect(self, pending):
        """COLLECT HALF of :meth:`step`: force the pending dispatch's
        results, emit/account/retire, and close the step's fault
        boundary. ``pending=None`` (the step already completed in
        :meth:`step_dispatch`) just reports whether work remains."""
        if pending is None:
            return self.scheduler.has_work
        try:
            self._decode_collect(pending)
        except InjectedFault as e:
            self._contain_fault(e)
        finally:
            self._sync_faults()
            self._sync_prefix_quarantines()
        return self.scheduler.has_work

    def run(self, requests=None):
        """Submit ``requests`` (if given) and drive until idle; returns
        the completed :class:`Request` list in submission order."""
        if requests is not None:
            for r in requests:
                if isinstance(r, Request):
                    self.scheduler.submit(r)
                    self._on_submitted(r)
                elif isinstance(r, dict):
                    self.submit(**r)
                else:
                    self.submit(r)
        while self.step():
            pass
        return self.completed

    def output_tokens(self, req):
        """prompt + generated ids as one int32 array (generate()-style
        row, truncated at retirement rather than pad-filled)."""
        return np.concatenate([req.prompt,
                               np.asarray(req.tokens, np.int32)])

    def engine_stats(self):
        out = dict(self.stats)
        out["pool"] = self.pool.fragmentation_stats()
        out["tp"] = self.tp
        out["quantum_collectives"] = {
            k: (dict(v) if isinstance(v, dict) else v)
            for k, v in self.quantum_collectives.items()}
        if self.tp > 1:
            out["pool_bytes_per_chip"] = \
                self.pool.per_chip_bytes_in_use()
        out["admitted"] = self.scheduler.admitted_total
        out["finished"] = self.scheduler.finished_total
        out["preempted"] = self.scheduler.preempted_total
        out["resumed"] = self.scheduler.resumed_total
        if self.stats["steps"]:
            out["mean_occupancy"] = (self.stats["occupancy_sum"]
                                     / self.stats["steps"])
        if self.d_pool is not None:
            out["draft_pool"] = self.d_pool.fragmentation_stats()
            out["spec_acceptance_rate"] = (
                self.stats["spec_accepted"]
                / max(self.stats["spec_proposed"], 1))
        if self.prefix_cache:
            out["prefix_cache"] = self.pool.prefix_cache_stats()
            if self.d_pool is not None:
                out["draft_prefix_cache"] = \
                    self.d_pool.prefix_cache_stats()
        out["resilience"] = self.resilience_report()
        return out

    def attribution(self):
        """The cost ledger's phase-attribution report
        (:meth:`~paddle_tpu.obs.attribution.CostLedger.report`) plus
        the raw counters its conservation invariants are checked
        against — emitted tokens by phase, wall seconds by phase
        (prefill / decode / spec_verify / preempt_recompute),
        novel/recompute/cached prefill work, rejected drafts, and the
        useful-fraction / prefix-savings / MFU gauges."""
        rep = self.obs.ledger.report()
        r = self.obs.registry
        rep["raw_counters"] = {
            "serving_tokens_emitted_total":
                int(r.get("serving_tokens_emitted_total").value()),
            "serving_prefill_tokens_total":
                int(self.stats["prefill_tokens"]),
            "serving_spec_proposed_total":
                int(self.stats["spec_proposed"]),
            "serving_spec_accepted_total":
                int(self.stats["spec_accepted"]),
            "serving_tokens_recomputed_total":
                int(r.get("serving_tokens_recomputed_total").value()),
        }
        return rep

    def decode_step_target(self):
        """(auditable step, example args) for ``analysis.check_budget``
        — the EXACT compiled object the serving hot loop dispatches,
        with the engine's live state as the example batch. A
        spec-disabled engine hands out the plain fallback quantum (the
        degraded-mode golden test fingerprints exactly this)."""
        if self._spec_disabled:
            return self._plain_audited, self._quantum_args()
        return self._audited, self._quantum_args()

    def multiquantum_step_target(self):
        """(auditable step, example args) for the MULTI-QUANTUM
        while_loop variant — the exact object `_dispatch_quantum`
        routes K > 1 dispatches through, fed by the same live-state
        argument tuple as the plain quantum (identical signature). The
        ``serving_multiquantum_step`` recipe fingerprints this."""
        if self._mq_audited is None:
            raise ValueError(
                "engine built without multi_quantum>1 (or with "
                "spec_draft): no multi-quantum variant to audit")
        return self._mq_audited, self._quantum_args()

    def health(self, now=None):
        """Evaluate the engine's SLOs over the obs sample series: the
        multi-window burn-rate report (state ``ok``/``warn``/
        ``critical`` + per-objective windows) the exporter's
        ``/healthz`` endpoint and the front door's shedding admission
        (serving/policy.py) consume. The engine must have been built
        with ``slo=``."""
        if self.slo is None:
            raise ValueError(
                "engine built without slo=: pass slo=True (stock "
                "objectives) or an SLOSet to evaluate health")
        report = self.slo.evaluate(self.obs, now=now)
        report["resilience"] = self.resilience_report()
        return report

    # -- resilience: containment, degradation ladders, recovery -----------
    def resilience_report(self):
        """Live view of the resilience tier: which degraded modes are
        active, what was quarantined/rebuilt, and the fault/retry/
        watchdog counters — carried by ``health()`` and
        ``engine_stats()``."""
        out = {
            "spec_disabled": self._spec_disabled,
            "spec_faults": self._spec_faults,
            "quarantined": list(self._quarantined),
            "pool_rebuilds": self._pool_rebuilds,
            "prefix_quarantines": self._prefix_quarantine_mark,
            "step_skips": self._step_skips,
            "retries_total": self._retries_total,
            "faults": self.faults.stats(),
        }
        if self.watchdog is not None:
            out["watchdog"] = self.watchdog.stats()
        return out

    def _audit_pools(self):
        """Ladder rung 3 — accounting drift: the pool's hard
        invariants (``_check_accounting``) normally fail-stop; under a
        resilience policy a drifted pool is REBUILT from the live block
        tables (the only ground truth tied to real sequence state) and
        serving continues. The prefix index is conservatively dropped
        with it — cached subtrees cannot be trusted after drift."""
        for pool in (self.pool, self.d_pool):
            if pool is None:
                continue
            try:
                pool._check_accounting()
            except RuntimeError:
                pool.rebuild_accounting()
                self._pool_rebuilds += 1
                now = self._now()
                self.obs.on_degrade("pool_rebuild", now)
                if self.flight is not None:
                    for r in self.scheduler.live():
                        self.flight.on_degrade(r, now,
                                               mode="pool_rebuild")

    def _sync_faults(self):
        """Fan the injector's journal delta out to obs counters (and
        the flight journal for poison-attributed entries)."""
        j = self.faults.journal
        if self._fault_mark >= len(j):
            return
        now = self._now()
        live = {str(r.req_id): r for r in self.scheduler.live()}
        for entry in j[self._fault_mark:]:
            self.obs.on_fault(entry["site"], entry["kind"])
            if self.flight is not None:
                req = live.get(str(entry.get("poison", "")))
                if req is not None:
                    self.flight.on_fault(req, now, site=entry["site"],
                                         kind=entry["kind"])
        self._fault_mark = len(j)

    def _sync_prefix_quarantines(self):
        """Ladder rung 2 — cached-KV corruption: the pools quarantine
        a corrupted cached subtree at verify time (paged_cache
        ``attach_prefix`` under ``kv_checksums``); the engine syncs the
        counter delta into obs here."""
        if not self.prefix_cache:
            return
        total = int(getattr(self.pool, "prefix_quarantines", 0))
        if self.d_pool is not None:
            total += int(getattr(self.d_pool, "prefix_quarantines", 0))
        if total > self._prefix_quarantine_mark:
            delta = total - self._prefix_quarantine_mark
            self._prefix_quarantine_mark = total
            self.obs.on_quarantine(self._now(), "prefix", count=delta)

    def _contain_fault(self, e):
        """Containment for an :class:`InjectedFault` that escaped the
        retry budget (``step()`` is the only caller). A poison fault is
        isolated — by batch bisect on the decode path, directly on the
        mixed path where the batch is host-built — and the culprit is
        finished with ``finish_reason="error"``; everyone else keeps
        serving. A transient fault (allocation failure, exhausted
        retries) drops the step on the floor: the injector fires BEFORE
        any device dispatch, allocation is idempotent, so the next step
        retries against intact state."""
        if e.poison is None:
            self._step_skips += 1
            return
        victim = None
        rows = self.scheduler.decoding()
        if e.site in ("decode", "spec_round") and len(rows) > 1:
            victim = self._isolate_poison()
        if victim is None:
            victim = next((r for r in self.scheduler.live()
                           if str(r.req_id) == str(e.poison)), None)
        if victim is not None:
            self._quarantine(victim)

    def _isolate_poison(self):
        """Batch-bisect quarantine: probe subsets of the decoding rows
        with REAL dispatches — a clean subset makes full progress (its
        tokens are emitted; the excluded rows ride along done-masked,
        completely inert through the dispatch) — until one row is
        isolated. Containment relies only on "a dispatch raises iff
        its active rows include a poison", never on the exception
        naming the culprit. Returns the isolated Request, or None if
        every probe ran clean."""
        suspects = list(self.scheduler.decoding())
        self._isolating = True
        try:
            while len(suspects) > 1:
                half = suspects[:len(suspects) // 2]
                rest = suspects[len(suspects) // 2:]
                if self._probe(half):
                    suspects = half
                else:
                    # half is clean and just made progress (some of it
                    # may even have finished) — the poison is in rest
                    suspects = [r for r in rest if not r.finished]
            if len(suspects) == 1 and self._probe(suspects):
                return suspects[0]
            return None
        finally:
            self._isolating = False

    def _probe(self, subset):
        """One real dispatch restricted to ``subset``; True if an
        injected fault fired (no progress), False after a clean
        dispatch whose tokens were emitted."""
        try:
            self._decode_quantum(include=subset)
        except InjectedFault:
            return True
        return False

    def _quarantine(self, req):
        """Finish one poison request with ``finish_reason="error"``
        and keep serving everyone else: its blocks return to every
        pool through the normal retire path, obs records the bad
        outcome (the error rate burns the SLO error budget), and the
        injector is cured so probes stop raising."""
        now = self._now()
        req.finished = True
        req.finish_reason = "error"
        if req.finish_time is None:
            req.finish_time = now
        self.faults.cure(req.req_id)
        self._quarantined.append(str(req.req_id))
        self.obs.on_quarantine(now, "poison")
        if self.flight is not None:
            self.flight.on_fault(req, now, site="quarantine",
                                 kind="poison")
        if req.slot is not None:
            self._retire_finished()

    def _note_spec_fault(self):
        """Ladder rung 1 — repeated spec-round faults (injected raises
        or watchdog trips) one-way degrade to the plain quantum."""
        if self.spec_draft is None or self._spec_disabled:
            return
        self._spec_faults += 1
        if (self.resilience is not None
                and self._spec_faults
                >= self.resilience.spec_fault_threshold):
            self._disable_spec()

    def _disable_spec(self):
        """Fall back from the speculative round to the PLAIN decode
        quantum — the same compiled family a ``spec_draft=None`` build
        jits, so no new golden. In-flight state carries over unchanged:
        the target pool holds every accepted token's KV, greedy streams
        continue bit-exact (the spec greedy arm already emits the
        target's own argmax stream), and the draft pool simply stops
        growing (its blocks free on retire/preempt as usual — ``free``
        is a no-op for sequences that never ensured draft blocks)."""
        if self._spec_disabled or self.spec_draft is None:
            return
        self._spec_disabled = True
        cfg = self.model.config
        self._plain_quantum = jax.jit(self._make_quantum(),
                                      donate_argnums=(0, 1, 2, 3))
        self._plain_audited = _AuditedStep(
            self._plain_quantum,
            n_donatable=(4 if self.pool.quantized else 2)
            * cfg.num_hidden_layers,
            mesh=self.mesh)
        now = self._now()
        self.obs.on_degrade("spec_disabled", now)
        if self.flight is not None:
            for r in self.scheduler.live():
                self.flight.on_degrade(r, now, mode="spec_disabled")

    def _guarded_dispatch(self, kind, rows, quanta=1):
        """One quantum dispatch under the resilience envelope: the
        injector's pre-dispatch check (faults fire BEFORE any donated
        buffer is consumed, so a retry re-runs against intact state),
        exponential-backoff retries for transient injected faults, and
        the wall-clock watchdog. Real exceptions propagate untouched —
        fail-stop is preserved for anything the injector didn't
        cause. Isolation probes never retry (the raise IS the probe
        signal), and poison faults escalate immediately. ``quanta > 1``
        dispatches the multi-quantum variant and normalizes the
        watchdog wall by the quantum count, so a K-quantum dispatch is
        judged against the same per-quantum calibration as K singles."""
        rids = [r.req_id for r in rows]
        pol = self.resilience
        attempt = 0
        while True:
            t0 = self._now()
            try:
                self.faults.before_dispatch(kind, rids)
                out = self._dispatch_quantum(quanta)
            except InjectedFault as e:
                if kind == "spec_round" and e.poison is None:
                    self._note_spec_fault()
                    if self._spec_disabled:
                        # the fault just crossed the disable threshold:
                        # a retry here would dispatch the PLAIN quantum
                        # under the spec-round caller — skip the step
                        # instead; the next step takes the plain path
                        raise
                if (self._isolating or e.poison is not None
                        or pol is None or attempt >= pol.max_retries):
                    raise
                delay = pol.backoff_s(attempt)
                attempt += 1
                self._retries_total += 1
                self.obs.on_retry(kind, attempt)
                if self.flight is not None:
                    now = self._now()
                    for r in rows:
                        self.flight.on_retry(r, now, kind=kind,
                                             attempt=attempt,
                                             backoff_s=delay)
                pol.sleep(delay)
                continue
            if self.watchdog is not None:
                dt = (self._now() - t0) / quanta
                if self.watchdog.check(kind, dt):
                    self.obs.on_watchdog(kind, dt)
                    if kind == "spec_round":
                        self._note_spec_fault()
            return out

    # -- crash recovery: snapshot / restore --------------------------------
    def snapshot(self):
        """JSON-able crash-recovery image of the SCHEDULER tier: every
        in-flight request's identity, generation params, and
        emitted-so-far tokens (plus completed-request summaries for
        audit). Device state is deliberately NOT captured — a restored
        engine re-admits each in-flight request through the existing
        RECOMPUTE-ON-RESUME machinery (``Request.begin_resume``:
        re-prefill ``prompt + tokens``, continue via
        ``fold_in(key, n_emitted)``), so greedy output streams are
        bit-exact vs the uninterrupted run without serializing a single
        pool buffer."""
        def req_state(req):
            return {
                "req_id": str(req.req_id),
                "prompt": [int(t) for t in np.asarray(req.prompt)],
                "max_new_tokens": int(req.max_new_tokens),
                "seed": int(req.seed),
                "priority": int(req.priority),
                "temperature": (None if req.temperature is None
                                else float(req.temperature)),
                "stop_token_ids": (sorted(req.stop_token_ids)
                                   if req.stop_token_ids else None),
                "stop_sequences": ([list(s) for s in req.stop_sequences]
                                   if req.stop_sequences else None),
                "tokens": [int(t) for t in req.tokens],
                "preemptions": int(req.preemptions),
            }

        inflight = list(self.scheduler.live()) + list(
            self.scheduler.waiting)
        return {
            "version": 1,
            "kind": "serving_engine_snapshot",
            "num_slots": self.config.num_slots,
            "block_size": self.pool.block_size,
            "max_context": self.max_context,
            "prefill_chunk": self.config.prefill_chunk,
            "decode_quantum": self.config.decode_quantum,
            "decode_strategy": self.decode_strategy,
            "top_k": self.top_k, "top_p": self.top_p,
            "temperature": self.temperature,
            "eos_token_id": self.eos_token_id,
            "spec_gamma": self.spec_gamma,
            "prefix_cache": self.prefix_cache,
            "per_request_sampling": self._per_request_sampling,
            "quantize": self.quantize,
            "kv_dtype": self.kv_dtype,
            "submitted_total": self.scheduler._submitted_total,
            "inflight": [req_state(r) for r in inflight],
            "completed": [{"req_id": str(r.req_id),
                           "tokens": [int(t) for t in r.tokens],
                           "finish_reason": r.finish_reason}
                          for r in self.completed],
        }

    @classmethod
    def restore(cls, snap, model, spec_draft=None, **overrides):
        """Build a FRESH engine from a :meth:`snapshot` and re-admit
        every in-flight request via recompute-on-resume. ``model`` (and
        ``spec_draft``) are re-supplied by the caller — params are not
        part of the snapshot; ``overrides`` adjust any constructor
        kwarg (e.g. ``resilience=True``, ``flight=True``). Completed
        summaries ride the snapshot for audit but are not
        re-materialized."""
        if snap.get("kind") != "serving_engine_snapshot":
            raise ValueError(
                "not a serving engine snapshot (kind="
                f"{snap.get('kind')!r})")
        kwargs = dict(
            num_slots=snap["num_slots"], block_size=snap["block_size"],
            max_context=snap["max_context"],
            prefill_chunk=snap["prefill_chunk"],
            decode_quantum=snap["decode_quantum"],
            decode_strategy=snap["decode_strategy"],
            top_k=snap["top_k"], top_p=snap["top_p"],
            temperature=snap["temperature"],
            eos_token_id=snap["eos_token_id"],
            spec_gamma=snap["spec_gamma"],
            prefix_cache=snap["prefix_cache"],
            per_request_sampling=snap["per_request_sampling"],
            quantize=snap.get("quantize"),
            kv_dtype=snap.get("kv_dtype"))
        kwargs.update(overrides)
        eng = cls(model, spec_draft=spec_draft, **kwargs)
        now = eng._now()
        for st in snap["inflight"]:
            req = Request(
                np.asarray(st["prompt"], np.int32),
                max_new_tokens=st["max_new_tokens"],
                req_id=st["req_id"], seed=st["seed"],
                priority=st["priority"],
                temperature=st["temperature"],
                stop_token_ids=st["stop_token_ids"],
                stop_sequences=st["stop_sequences"],
                arrival_time=now)
            req.tokens = list(st["tokens"])
            req.preemptions = int(st["preemptions"])
            if req.tokens or req.preemptions:
                # the restart IS a whole-engine preemption: re-admission
                # re-prefills prompt + tokens; the recomputed tokens are
                # NOT re-emitted and the continuation stays bit-exact
                req.begin_resume()
            eng.scheduler.submit(req)
            eng._on_submitted(req)
            if eng.flight is not None:
                eng.flight.on_restore(req, now,
                                      tokens_resumed=len(req.tokens))
        eng.scheduler._submitted_total = max(
            eng.scheduler._submitted_total,
            int(snap.get("submitted_total", 0)))
        eng.obs.on_restore(now, len(snap["inflight"]))
        return eng

    # -- admission + prefill ----------------------------------------------
    def _admit(self):
        now = self._now()
        for req in self.scheduler.try_admit():
            resumed = req.preemptions > 0
            req.admit_time = now
            if resumed:
                self.obs.on_resume(req, now)
                if self.flight is not None:
                    self.flight.on_resume(
                        req, now, slot=req.slot,
                        prefill_tokens=req.prefill_target)
            else:
                self.obs.on_admit(req, now)
                if self.flight is not None:
                    st = self.pool.fragmentation_stats()
                    reserved = self.scheduler._reservations.get(req)
                    cached_blk = self.pool.held_blocks(req.req_id)
                    self.flight.on_admit(
                        req, now, queue_wait=now - req.arrival_time,
                        blocks_reserved=reserved,
                        pool_free_blocks=st["free_blocks"],
                        pool_blocks_in_use=st["blocks_in_use"],
                        cached_blocks=cached_blk,
                        novel_blocks=(None if reserved is None
                                      else reserved - cached_blk))
            slot = req.slot
            cached = 0
            if self.prefix_cache and req.cached_prefix_tokens:
                # never skip the WHOLE prefill source: the final
                # position is re-prefilled (a one-token chunk) so
                # completion still emits a token — and that write is
                # the designed copy-on-write trigger for the tail
                # shared block when the entire prompt was cached
                cached = min(req.cached_prefix_tokens,
                             req.prefill_target - 1)
                req.prefill_pos = cached
                self.obs.on_cached_prefill(req, cached)
            self._seq_lens[slot] = cached
            self._n_gen[slot] = 0
            self._done[slot] = True  # not decodable until prefill ends
            self._max_new[slot] = req.max_new_tokens
            self._keys[slot] = np.asarray(jax.random.PRNGKey(req.seed))
            self._temps[slot] = (self.temperature
                                 if req.temperature is None
                                 else req.temperature)

    def _mixed_forward(self, model, pool, tables, rotary, enc_lens,
                       dec_lens, this_time, ids, total):
        """One mixed prefill(+decode) forward of ``model`` over
        ``pool`` through ``block_multihead_attention`` — shared by the
        target and (in the speculative arm) the DRAFT, which must
        ingest exactly the same rows so its cache stays in lockstep
        with the target's. Returns the (1, T, E) hidden states; the
        mutated pool Tensors are written back as the new truth."""
        import paddle_tpu as paddle
        from ..incubate.nn.functional import block_multihead_attention

        cfg = model.config
        h, hk, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                    cfg.head_dim)
        kc_t = [Tensor(pool.k_pools[i], stop_gradient=True)
                for i in range(cfg.num_hidden_layers)]
        vc_t = [Tensor(pool.v_pools[i], stop_gradient=True)
                for i in range(cfg.num_hidden_layers)]
        ks_t = vs_t = None
        if pool.quantized:
            # int8 pool: thread the per-row scale pools through the
            # fused op; each written row quantizes in-graph and the
            # mutated scale pools come back as the new truth
            ks_t = [Tensor(pool.k_scales[i], stop_gradient=True)
                    for i in range(cfg.num_hidden_layers)]
            vs_t = [Tensor(pool.v_scales[i], stop_gradient=True)
                    for i in range(cfg.num_hidden_layers)]
        common = dict(
            seq_lens_encoder=paddle.to_tensor(
                np.asarray(enc_lens, np.int32)),
            seq_lens_decoder=paddle.to_tensor(
                np.asarray(dec_lens, np.int32)),
            seq_lens_this_time=paddle.to_tensor(
                np.asarray(this_time, np.int32)),
            block_tables=Tensor(tables, stop_gradient=True),
            rotary_embs=rotary,
            use_neox_rotary_style=True,  # the model's rope layout
            num_heads=h, kv_num_heads=hk, head_dim=d,
        )
        # under tp the eager prefill layers place their activations via
        # the mp layers' constraints, which read the global mesh
        scope = (MeshScope(self.mesh) if self.mesh is not None
                 else contextlib.nullcontext())
        with scope, autograd.no_grad():
            core = model.llama
            hidden = core.embed_tokens(
                paddle.to_tensor(ids[None, :]))          # (1, T, E)
            for i, layer in enumerate(core.layers):
                attn = layer.self_attn
                residual = hidden
                x = layer.input_layernorm(hidden)
                q = attn.q_proj(x)
                k = attn.k_proj(x)
                v = attn.v_proj(x)
                qkv = paddle.concat([q, k, v], axis=-1) \
                    .reshape([total, (h + 2 * hk) * d])
                scales = ({} if ks_t is None else
                          dict(cache_k_scale_pool=ks_t[i],
                               cache_v_scale_pool=vs_t[i]))
                att = block_multihead_attention(
                    qkv, kc_t[i], vc_t[i], **common, **scales)
                att3 = att.reshape([1, total, h * d])
                hidden = residual + attn.o_proj(att3)
                hidden = hidden + layer.mlp(
                    layer.post_attention_layernorm(hidden))
            hidden = core.norm(hidden)
        # the mutated pool Tensors are the new truth (re-pinned to the
        # pool's mesh layout under tp — the quantum donates them and
        # expects the exact layout it was compiled for)
        for i in range(cfg.num_hidden_layers):
            pool.k_pools[i] = pool._pin(kc_t[i]._value)
            pool.v_pools[i] = pool._pin(vc_t[i]._value)
            if ks_t is not None:
                pool.k_scales[i] = pool._pin_scale(ks_t[i]._value)
                pool.v_scales[i] = pool._pin_scale(vs_t[i]._value)
        return hidden

    def _mixed_step(self):
        """One chunk of prefill for every prefilling slot, one decode
        token for every in-flight slot — a single MIXED batch through
        ``block_multihead_attention`` per layer (chunked prefill
        interleaved with decode, the reference's serving batch shape).
        The speculative arm pushes the SAME batch through the draft
        model into the draft pool (token selection stays the target's;
        the draft forward exists only for its KV writes)."""
        model = self.model
        t0 = self._now()
        self.stats["mixed_steps"] += 1
        chunk = self.config.prefill_chunk
        pre = self.scheduler.prefilling()
        dec = self.scheduler.decoding()
        rows = pre + dec
        spec = self.spec_draft is not None and not self._spec_disabled
        # the mixed step's fault boundary: BEFORE any pool mutation, so
        # a raised step retries cleanly from the next step()
        self.faults.before_dispatch("mixed", [r.req_id for r in rows])
        toks, this_time, enc_lens, dec_lens = [], [], [], []
        # cost-ledger work split: a resumed row's chunk re-computes KV
        # a preemption dropped (recompute debt); a fresh row's chunk is
        # novel prefill work (obs/attribution.py)
        novel_toks = recompute_toks = 0
        for req in pre:
            n = min(chunk, req.prefill_target - req.prefill_pos)
            if req.preemptions > 0:
                recompute_toks += n
            else:
                novel_toks += n
            toks.append(
                req.prefill_src[req.prefill_pos:req.prefill_pos + n])
            this_time.append(n)
            enc_lens.append(n)
            dec_lens.append(req.prefill_pos)
            self.pool.ensure(req.req_id, req.prefill_pos + n)
            if spec:
                self.d_pool.ensure(req.req_id, req.prefill_pos + n)
            if self.prefix_cache:
                # copy-on-write before the forward: the chunk's KV
                # writes must never land in a block another holder
                # (sequence or prefix index) still maps
                self.pool.make_writable(req.req_id, req.prefill_pos,
                                        req.prefill_pos + n)
                if spec:
                    self.d_pool.make_writable(
                        req.req_id, req.prefill_pos,
                        req.prefill_pos + n)
        for req in dec:
            slot = req.slot
            toks.append(np.asarray([self._last_tok[slot]], np.int32))
            this_time.append(1)
            enc_lens.append(0)
            dec_lens.append(int(self._seq_lens[slot]))
            self.pool.ensure(req.req_id, int(self._seq_lens[slot]) + 1)
            if spec:
                self.d_pool.ensure(req.req_id,
                                   int(self._seq_lens[slot]) + 1)
            if self.prefix_cache:
                seq = int(self._seq_lens[slot])
                self.pool.make_writable(req.req_id, seq, seq + 1)
                if spec:
                    self.d_pool.make_writable(req.req_id, seq, seq + 1)
        ids = np.concatenate(toks).astype(np.int32)
        total = int(ids.shape[0])
        self.stats["prefill_tokens"] += int(sum(enc_lens))
        cu = np.concatenate([[0], np.cumsum(this_time)]).astype(np.int32)
        row_ids = [r.req_id for r in rows]
        tables = self.pool.block_table_array(
            row_ids, pad_to=self._table_width)
        hidden = self._mixed_forward(
            model, self.pool, tables, self._rotary, enc_lens, dec_lens,
            this_time, ids, total)
        if spec:
            d_tables = self.d_pool.block_table_array(
                row_ids, pad_to=self._table_width)
            self._mixed_forward(
                self.spec_draft, self.d_pool, d_tables, self._d_rotary,
                enc_lens, dec_lens, this_time, ids, total)

        # logits only where a next token is due: rows completing their
        # prefill this chunk, and every decode row
        need = [i for i, req in enumerate(rows)
                if (i >= len(pre)) or
                (req.prefill_pos + this_time[i] >= req.prefill_target)]
        if need:
            last_idx = np.asarray([cu[i + 1] - 1 for i in need], np.int32)
            scope = (MeshScope(self.mesh) if self.mesh is not None
                     else contextlib.nullcontext())
            with scope, autograd.no_grad():
                hs = Tensor(hidden._value[0, last_idx],
                            stop_gradient=True)
                logits = model.lm_head(hs)._value        # (R, V)
            nxt = self._select_host(logits,
                                    [rows[i] for i in need])
        now = self._now()
        emitted = prefill_emitted = 0
        for i, req in enumerate(rows):
            slot = req.slot
            if i < len(pre):
                req.prefill_pos += this_time[i]
                self._seq_lens[slot] = req.prefill_pos
                if self.flight is not None:
                    self.flight.on_prefill_chunk(
                        req, now, this_time[i], req.prefill_pos)
                if req.prefill_pos >= req.prefill_target:
                    if self.prefix_cache:
                        # the whole prefill source is in the pool now:
                        # publish its full blocks into the prefix index
                        # (both pools — lockstep) so the next request
                        # with this prefix aliases instead of computing
                        self.pool.publish_prefix(req.req_id,
                                                 req.prefill_src)
                        if spec:
                            self.d_pool.publish_prefix(
                                req.req_id, req.prefill_src)
                        self.scheduler.clear_cow_debt(req)
                    tok = int(nxt[need.index(i)])
                    if req.first_token_time is None:
                        # TTFT observes exactly ONCE per request — a
                        # resumed request's re-prefill completion emits
                        # a continuation token, not a first token
                        req.first_token_time = now
                        self.obs.on_first_token(req, now)
                        if self.flight is not None:
                            self.flight.on_first_token(
                                req, now, now - req.arrival_time)
                    self._emit(req, tok)
                    emitted += 1
                    prefill_emitted += 1
                    self._record_host(slot, req, tok)
            else:
                tok = int(nxt[need.index(i)])
                self._seq_lens[slot] += 1  # last_tok entered the cache
                self._emit(req, tok)
                emitted += 1
                self._record_host(slot, req, tok)
        self.obs.on_quantum(
            "mixed", t0, now, emitted, len(rows),
            breakdown={"prefill_emitted": prefill_emitted,
                       "decode_emitted": emitted - prefill_emitted,
                       "novel_tokens": novel_toks,
                       "recompute_tokens": recompute_toks,
                       "decode_rows": len(dec)})
        if self.watchdog is not None and self.watchdog.check(
                "mixed", now - t0):
            self.obs.on_watchdog("mixed", now - t0)
        self._retire_finished()

    def _emit(self, req, tok):
        """Append ONE generated token to a request's stream (retirement
        rule included) and count it — the obs token counter matches the
        emitted streams exactly because every append goes through here.
        The front door's ``token_sink`` fires on the same boundary (the
        streaming API's per-token push)."""
        req.record(tok, self.eos_token_id)
        self.obs.on_token(req)
        if self.token_sink is not None:
            self.token_sink(req, int(tok))

    def _record_host(self, slot, req, tok):
        self._last_tok[slot] = tok
        self._n_gen[slot] = len(req.tokens)
        self._done[slot] = req.finished

    def _select_host(self, logits, rows):
        """First-token / mixed-step selection with the SAME math as the
        device quantum: argmax, or filtered categorical keyed by each
        slot's fold_in(key, n_emitted)."""
        if self.decode_strategy == "greedy":
            return np.asarray(jnp.argmax(logits, axis=-1)).astype(np.int32)
        if self._per_request_sampling:
            temps = jnp.asarray(np.asarray(
                [self._temps[r.slot] for r in rows], np.float32))
            filt = _filter_logits(
                logits.astype(jnp.float32)
                / jnp.maximum(temps, 1e-6)[:, None],
                self.top_k, self.top_p, None)
        else:
            filt = _filter_logits(logits, self.top_k, self.top_p,
                                  self.temperature)
        keys = jnp.asarray(np.stack(
            [self._keys[r.slot] for r in rows]))
        steps = jnp.asarray(np.asarray(
            [len(r.tokens) for r in rows], np.int32))
        keys = jax.vmap(jax.random.fold_in)(keys, steps)
        samp = jax.vmap(jax.random.categorical)(keys, filt)
        return np.asarray(samp).astype(np.int32)

    # -- the jitted decode quantum ----------------------------------------
    def _select_device(self, logits, keys, n_gen, temps=None):
        if self.decode_strategy == "greedy":
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if temps is not None:
            # per-slot temperature (the front-door quantum variant):
            # same scale-then-filter order — and the same f32 division
            # — as the engine-wide path, so a uniform temps row
            # replays the engine-wide engine bit-for-bit
            filt = _filter_logits(
                logits.astype(jnp.float32)
                / jnp.maximum(temps, 1e-6)[:, None],
                self.top_k, self.top_p, None)
        else:
            filt = _filter_logits(logits, self.top_k, self.top_p,
                                  self.temperature)
        step_keys = jax.vmap(jax.random.fold_in)(keys, n_gen)
        return jax.vmap(jax.random.categorical)(
            step_keys, filt).astype(jnp.int32)

    def _make_quantum(self, multi=None):
        """Build the decode-quantum callable. ``multi=None``: the plain
        single-quantum scan, exactly as ever. ``multi=K``: the
        MULTI-QUANTUM driver — the same scan wrapped in a
        ``lax.while_loop`` that runs up to K quanta per dispatch,
        short-circuiting on-device when every row's retirement mask
        sets; tokens land in a (K, T, S) buffer and the loop counter
        comes back so the host can account exactly the quanta that
        ran. The K=1 graph is untouched — both wrappers call the same
        ``scan_steps``."""
        model = self.model
        scratch = self._scratch_block
        t_steps = self.config.decode_quantum
        n_slots = self.config.num_slots
        attn_impl = self.attn_impl
        has_eos = self.eos_token_id is not None
        eos = -1 if self.eos_token_id is None else int(self.eos_token_id)

        def scan_steps(kc, vc, ks, vs, p_vals, tables, seq_lens,
                       last_tok, n_gen, done, max_new, keys, temps):
            # ks/vs are the int8 pool's per-row scale pools; on a float
            # engine they are EMPTY tuples — zero avals in the carry,
            # so the compiled graph (and golden) is byte-identical
            def body(carry, _):
                kc, vc, ks, vs, seq_lens, last_tok, n_gen, done = carry
                live = ~done
                with autograd.no_grad():
                    def fwd(tok_t):
                        return paged_decode_math(
                            model, scratch, tok_t, seq_lens, tables,
                            kc, vc, live, ks=ks, vs=vs,
                            attn_impl=attn_impl)

                    (logits, kc2, vc2, ks2, vs2), _ = functional_call(
                        model, fwd,
                        [Tensor(last_tok[:, None], stop_gradient=True)],
                        {}, p_vals, [])
                nxt = self._select_device(logits, keys, n_gen, temps)
                nxt = jnp.where(done, last_tok, nxt).astype(jnp.int32)
                n_gen2 = n_gen + live.astype(jnp.int32)
                done2 = done | (n_gen2 >= max_new)
                if has_eos:
                    done2 = done2 | (live & (nxt == eos))
                seq_lens2 = seq_lens + live.astype(jnp.int32)
                return (kc2, vc2, ks2, vs2, seq_lens2, nxt, n_gen2,
                        done2), nxt

            (kc, vc, ks, vs, seq_lens, last_tok, n_gen, done), toks = \
                jax.lax.scan(
                    body,
                    (kc, vc, tuple(ks), tuple(vs), seq_lens, last_tok,
                     n_gen, done),
                    None, length=t_steps)
            return (kc, vc, ks, vs, seq_lens, last_tok, n_gen, done,
                    toks)

        def multi_steps(kc, vc, ks, vs, p_vals, tables, seq_lens,
                        last_tok, n_gen, done, max_new, keys, temps):
            # K quanta per dispatch: the host round-trips device state
            # untouched between steady-state quanta, so folding the
            # round-trips into a while_loop changes no math — streams
            # stay bit-identical to K sequential dispatches. The
            # all-done cond is the on-device early exit; the returned
            # counter tells the host how many quanta to account.
            k_max = int(multi)
            buf0 = jnp.zeros((k_max, t_steps, n_slots), jnp.int32)

            def cond(carry):
                qi, done = carry[0], carry[8]
                return (qi < k_max) & ~jnp.all(done)

            def body(carry):
                (qi, kc, vc, ks, vs, seq_lens, last_tok, n_gen, done,
                 buf) = carry
                (kc, vc, ks, vs, seq_lens, last_tok, n_gen, done,
                 toks) = scan_steps(kc, vc, ks, vs, p_vals, tables,
                                    seq_lens, last_tok, n_gen, done,
                                    max_new, keys, temps)
                buf = jax.lax.dynamic_update_slice(
                    buf, toks[None], (qi, 0, 0))
                return (qi + 1, kc, vc, ks, vs, seq_lens, last_tok,
                        n_gen, done, buf)

            (qi, kc, vc, ks, vs, seq_lens, last_tok, n_gen, done,
             buf) = jax.lax.while_loop(
                cond, body,
                (jnp.int32(0), kc, vc, tuple(ks), tuple(vs), seq_lens,
                 last_tok, n_gen, done, buf0))
            return (kc, vc, ks, vs, seq_lens, last_tok, n_gen, done,
                    buf, qi)

        inner = scan_steps if multi is None else multi_steps
        if self._per_request_sampling:
            # the front-door variant: per-slot temperature rides the
            # existing per-slot state as ONE extra (S,) f32 input —
            # its own recipe (serving_frontdoor_step) and golden pin
            # this signature; the default quantum below is untouched
            def quantum(kc, vc, ks, vs, p_vals, tables, seq_lens,
                        last_tok, n_gen, done, max_new, keys, temps):
                return inner(kc, vc, ks, vs, p_vals, tables,
                             seq_lens, last_tok, n_gen, done,
                             max_new, keys, temps)
        else:
            def quantum(kc, vc, ks, vs, p_vals, tables, seq_lens,
                        last_tok, n_gen, done, max_new, keys):
                return inner(kc, vc, ks, vs, p_vals, tables,
                             seq_lens, last_tok, n_gen, done,
                             max_new, keys, None)

        return quantum

    def _dev(self, a):
        """Device view of one host mirror: plain uncommitted transfer on
        a single chip; committed REPLICATED under tp, so every dispatch
        hands the compiled quantum the exact input layouts it was built
        for."""
        v = jnp.asarray(a)
        if self._rep_sharding is None:
            return v
        return jax.device_put(v, self._rep_sharding)

    def _quantum_args(self):
        # the scale tuples ride right after their pool's v_pools (empty
        # on a float engine — no avals, goldens untouched); donation
        # covers all leading pool pytrees
        if self.spec_draft is not None and not self._spec_disabled:
            return (list(self.pool.k_pools), list(self.pool.v_pools),
                    tuple(self.pool.k_scales),
                    tuple(self.pool.v_scales),
                    list(self.d_pool.k_pools),
                    list(self.d_pool.v_pools),
                    tuple(self.d_pool.k_scales),
                    tuple(self.d_pool.v_scales),
                    self._p_vals, self._d_p_vals,
                    self._dev(self._tables),
                    self._dev(self._d_tables),
                    self._dev(self._seq_lens),
                    self._dev(self._last_tok),
                    self._dev(self._n_gen), self._dev(self._done),
                    self._dev(self._max_new),
                    self._dev(self._keys))
        args = (list(self.pool.k_pools), list(self.pool.v_pools),
                tuple(self.pool.k_scales), tuple(self.pool.v_scales),
                self._p_vals, self._dev(self._tables),
                self._dev(self._seq_lens),
                self._dev(self._last_tok), self._dev(self._n_gen),
                self._dev(self._done), self._dev(self._max_new),
                self._dev(self._keys))
        if self._per_request_sampling:
            args = args + (self._dev(self._temps),)
        return args

    def _dispatch_quantum(self, quanta=1):
        """Run ONE quantum dispatch. Single chip: the jitted callable,
        exactly as before. Under tp: inside the engine's MeshScope
        (the first call's trace needs the mesh installed for the mp
        layers' constraints) and through the build-time compiled
        executable when present — the census compile doubles as the
        serving executable. After a spec-disable degrade the PLAIN
        fallback quantum dispatches instead (the tp census executable
        was compiled for the spec signature). ``quanta > 1`` routes to
        the multi-quantum while_loop variant (same argument tuple)."""
        quantum = (self._plain_quantum if self._spec_disabled
                   else self._quantum)
        if quanta > 1:
            quantum = self._mq_quantum
        if self.mesh is None:
            return quantum(*self._quantum_args())
        with MeshScope(self.mesh):
            if (self._quantum_compiled is not None
                    and not self._spec_disabled and quanta == 1):
                return self._quantum_compiled(*self._quantum_args())
            return quantum(*self._quantum_args())

    def _spec_round_step(self, include=None):
        """Dispatch ONE jitted speculative round (draft-γ scan + target
        verify + in-graph acceptance and cache roll forward/back); the
        host runs only here, at the admit/retire boundary — variable
        per-round token yield composes with the same retirement masks
        as the plain quantum. ``include`` restricts the round to a
        subset of the decoding rows (the bisect-quarantine probe path):
        excluded rows ride along done-masked — inert through the
        dispatch — and their host state is restored afterwards."""
        g = self.spec_gamma
        t0 = self._now()
        self.stats["spec_rounds"] += 1
        rows = self.scheduler.decoding()
        excluded = []
        if include is not None:
            keep = {id(r) for r in include}
            excluded = [r for r in rows if id(r) not in keep]
            rows = [r for r in rows if id(r) in keep]
            for r in excluded:
                self._done[r.slot] = True
        try:
            for req in rows:
                slot = req.slot
                # cover the round's worst-case writes (γ proposals past
                # the accepted history) in BOTH pools before entering
                # the device loop — tables are static inside
                need = int(self._seq_lens[slot]) + g + 1
                for pool, tables in ((self.pool, self._tables),
                                     (self.d_pool, self._d_tables)):
                    if need > pool.seq_len(req.req_id):
                        pool.ensure(req.req_id, need)
                    if self.prefix_cache:
                        pool.make_writable(
                            req.req_id, int(self._seq_lens[slot]), need)
                    row = pool.block_table_array(
                        [req.req_id], pad_to=self._table_width)
                    tables[slot] = np.asarray(row)[0][
                        :self._table_width]
            (t_kc, t_vc, t_ks, t_vs, d_kc, d_vc, d_ks, d_vs, seq_lens,
             last_tok, n_gen, done, stream, counts,
             acc) = self._guarded_dispatch("spec_round", rows)
        except BaseException:
            for r in excluded:
                self._done[r.slot] = r.finished
            raise
        self.pool.k_pools = list(t_kc)
        self.pool.v_pools = list(t_vc)
        self.d_pool.k_pools = list(d_kc)
        self.d_pool.v_pools = list(d_vc)
        if self.pool.quantized:
            self.pool.k_scales = list(t_ks)
            self.pool.v_scales = list(t_vs)
            self.d_pool.k_scales = list(d_ks)
            self.d_pool.v_scales = list(d_vs)
        stream = np.asarray(stream)                      # (S, γ+1) sync
        counts = np.asarray(counts)
        acc = np.asarray(acc)
        self._seq_lens = np.asarray(seq_lens).copy()
        self._last_tok = np.asarray(last_tok).copy()
        self._n_gen = np.asarray(n_gen).copy()
        self._done = np.asarray(done).copy()
        for r in excluded:
            # a masked row's device state carried through unchanged;
            # only its done flag was forced — restore the host truth
            self._done[r.slot] = r.finished
        self.stats["quantum_tokens"] += int(counts.sum())
        self.stats["spec_proposed"] += g * len(rows)
        self.stats["spec_accepted"] += int(acc.sum())
        now = self._now()
        emitted = 0
        for req in rows:
            slot = req.slot
            got = 0
            for k in range(int(counts[slot])):
                if req.finished:
                    break
                self._emit(req, int(stream[slot, k]))
                emitted += 1
                got += 1
            if self.flight is not None:
                self.flight.on_spec_round(
                    req, now, proposed=g, accepted=int(acc[slot]),
                    emitted=got)
            if req.finished:
                req.finish_time = now
        self.obs.on_quantum("spec_round", t0, now, emitted, len(rows))
        self.obs.on_spec_round(now, g * len(rows), int(acc.sum()))
        self._retire_finished()

    def _choose_k(self):
        """How many decode quanta the NEXT dispatch may run on-device.
        The multi-quantum cap applies only when the scheduler is in
        steady state (batch composition CANNOT change before the
        dispatch lands) and no host seam needs per-quantum visibility:
        an armed fault injector or an in-flight bisect probe forces
        per-quantum dispatch so fault attribution stays exact."""
        if self._mq_quantum is None or self._isolating:
            return 1
        if self.faults.armed:
            return 1
        if not self.scheduler.steady_state():
            return 1
        return self._mq_max

    def _decode_quantum(self, include=None):
        """Dispatch + collect one decode step SYNCHRONOUSLY — the
        single-engine path and the bisect probe. The overlap tier
        (cluster pump, `step_dispatch`/`step_collect`) drives the two
        halves separately instead."""
        pending = self._decode_dispatch(include=include)
        if pending is not None:
            self._decode_collect(pending)

    def _decode_dispatch(self, include=None):
        """DISPATCH HALF of the decode step: grow block tables, enqueue
        the jitted quantum (K quanta when `_choose_k` allows), adopt
        the async donated pool outputs, and return a pending record for
        `_decode_collect` — WITHOUT forcing a host sync, so the device
        executes while the host moves on (the overlap the cluster pump
        exploits). ``include`` restricts the quantum to a subset of the
        decoding rows (the bisect-quarantine probe path): excluded rows
        ride along done-masked — inert through the dispatch — and
        their host state is restored at collect. A speculative round
        (host needs its acceptance counts to proceed) runs to
        completion here and returns None."""
        if self.spec_draft is not None and not self._spec_disabled:
            self._spec_round_step(include=include)
            return None
        t0 = self._now()
        t_steps = self.config.decode_quantum
        k = 1 if include is not None else self._choose_k()
        rows = self.scheduler.decoding()
        excluded = []
        if include is not None:
            keep = {id(r) for r in include}
            excluded = [r for r in rows if id(r) not in keep]
            rows = [r for r in rows if id(r) in keep]
            for r in excluded:
                self._done[r.slot] = True
        try:
            # grow each live slot's block table to cover the whole
            # dispatch (K quanta) before entering the device loop
            # (tables static inside); capped by the request's own
            # prompt+max_new bound, which admission already reserved —
            # K-wide growth can never oversubscribe the pool
            for req in rows:
                slot = req.slot
                cap = req.prompt_len + req.max_new_tokens - 1
                need = min(int(self._seq_lens[slot]) + k * t_steps, cap)
                row = self.pool.grow_decode_table(
                    req.req_id, need, int(self._seq_lens[slot]),
                    pad_to=self._table_width, cow=self.prefix_cache)
                self._tables[slot] = row[:self._table_width]
            out = self._guarded_dispatch("decode", rows, quanta=k)
        except BaseException:
            for r in excluded:
                self._done[r.slot] = r.finished
            raise
        if k > 1:
            (kc, vc, ks, vs, seq_lens, last_tok, n_gen, done, toks,
             nq) = out
        else:
            kc, vc, ks, vs, seq_lens, last_tok, n_gen, done, toks = out
            nq = None
        # adopt the donated pool outputs NOW (async handles — no sync):
        # the pre-dispatch buffers were consumed by donation
        self.pool.k_pools = list(kc)
        self.pool.v_pools = list(vc)
        if self.pool.quantized:
            self.pool.k_scales = list(ks)
            self.pool.v_scales = list(vs)
        return {"rows": rows, "excluded": excluded, "t0": t0,
                "t_disp": self._now(), "k": k,
                "out": (seq_lens, last_tok, n_gen, done, toks, nq)}

    def _decode_collect(self, pending):
        """COLLECT HALF of the decode step: force the device results
        (the ONE host sync per dispatch), refresh the host mirrors,
        emit every generated token, account the dispatch as the
        ``n_exec`` quanta that actually ran (obs histograms, cost
        ledger, host-gap gauge — each sub-quantum gets an equal slice
        of the wall, so the conservation invariants partition exactly),
        and retire finished rows."""
        rows, excluded = pending["rows"], pending["excluded"]
        t0, k = pending["t0"], pending["k"]
        seq_lens, last_tok, n_gen, done, toks, nq = pending["out"]
        t_steps = self.config.decode_quantum
        toks = np.asarray(toks)                          # sync
        self._seq_lens = np.asarray(seq_lens).copy()
        self._last_tok = np.asarray(last_tok).copy()
        self._n_gen = np.asarray(n_gen).copy()
        self._done = np.asarray(done).copy()
        for r in excluded:
            # a masked row's device state carried through unchanged;
            # only its done flag was forced — restore the host truth
            self._done[r.slot] = r.finished
        if k > 1:
            # (K, T, S) buffer + on-device loop counter: keep only the
            # quanta that ran before the all-done early exit fired
            n_exec = int(np.asarray(nq))
            toks = toks[:n_exec].reshape(-1, toks.shape[2])
            n_exec = max(n_exec, 1)
        else:
            n_exec = 1                                   # (T, S)
        self.stats["decode_quanta"] += n_exec
        self.stats["quantum_tokens"] += int(toks.shape[0]) * int(
            toks.shape[1])
        now = self._now()
        device_s = max(now - pending["t_disp"], 0.0)
        emitted_k = [0] * n_exec
        for req in rows:
            slot = req.slot
            got = 0
            for j in range(toks.shape[0]):
                if req.finished:
                    break
                self._emit(req, int(toks[j, slot]))
                emitted_k[j // t_steps] += 1
                got += 1
            if self.flight is not None and got:
                self.flight.on_quantum_tokens(req, now, got)
            if req.finished:
                req.finish_time = now
        # a K-quantum dispatch is K quanta to every seam downstream:
        # the sub-intervals partition [t0, now] exactly (last edge IS
        # `now`), so Σ phase seconds == histogram sums stays exact
        dt = (now - t0) / n_exec
        dev_dt = device_s / n_exec
        prev = t0
        for j in range(n_exec):
            edge = now if j == n_exec - 1 else t0 + (j + 1) * dt
            self.obs.on_quantum("decode", prev, edge, emitted_k[j],
                                len(rows), device_s=dev_dt)
            prev = edge
        self._retire_finished()

    def _retire_finished(self):
        now = self._now()
        for req in list(self.scheduler.live()):
            if req.finished:
                slot = req.slot
                if req.finish_time is None:
                    req.finish_time = now
                self.stats["generated_tokens"] += len(req.tokens)
                self.obs.on_retire(req, req.finish_time)
                if self.flight is not None:
                    self.flight.on_retire(
                        req, req.finish_time,
                        ttft=(req.first_token_time - req.arrival_time
                              if req.first_token_time is not None
                              else None),
                        e2e=req.finish_time - req.arrival_time,
                        reason=req.finish_reason)
                self._done[slot] = True
                self._max_new[slot] = 0
                self.scheduler.retire(req)
                self.completed.append(req)
