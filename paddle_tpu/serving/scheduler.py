"""Request state + admission scheduling for the continuous-batching
engine (reference: the serving loop around AnalysisPredictor /
``Predictor.run``'s fused_multi_transformer decode HOT LOOP — SURVEY.md
§2.6/§3.5; the scheduler itself mirrors the 2.6-era
BlockInferencePredictor's slot/block accounting — unverified, SURVEY §0).

Pure host-side bookkeeping: a FIFO admission queue, a fixed table of
``num_slots`` serving slots (the padded active set the jitted decode
step is compiled for), and conservative block accounting against the
shared :class:`~paddle_tpu.nlp.paged_cache.PagedKVCachePool` — a request
is admitted only when its WORST-CASE block demand
(``ceil((prompt + max_new) / block_size)``) fits under the pool capacity
left unreserved by in-flight requests, so the pool can never exhaust
mid-decode and no preemption path is needed. Retirement returns both the
reservation and the actual blocks (``pool.free``) for immediate reuse.
"""
from __future__ import annotations

from collections import deque

import numpy as np

__all__ = ["Request", "SchedulerConfig", "Scheduler"]


class Request:
    """One generation request riding the engine.

    Lifecycle: ``waiting`` (queued) -> ``prefill`` (admitted to a slot,
    prompt entering the pool chunk by chunk) -> ``decode`` (in the
    jitted quantum) -> ``finished`` (eos | max_new; blocks freed).
    """

    def __init__(self, prompt, max_new_tokens=32, req_id=None, seed=0,
                 arrival_time=0.0):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.req_id = req_id
        self.seed = int(seed)
        self.arrival_time = float(arrival_time)
        # mutable state
        self.slot = None
        self.prefill_pos = 0          # prompt tokens already in the pool
        self.tokens: list = []        # generated token ids (incl. eos)
        self.finished = False
        self.finish_reason = None     # "eos" | "length"
        self.admit_time = None
        self.first_token_time = None
        self.finish_time = None

    @property
    def prompt_len(self):
        return int(self.prompt.shape[0])

    @property
    def prefilling(self):
        return self.slot is not None and self.prefill_pos < self.prompt_len

    @property
    def decoding(self):
        return (self.slot is not None and not self.finished
                and self.prefill_pos >= self.prompt_len)

    def record(self, token, eos_token_id=None):
        """Append one emitted token and apply the retirement rule the
        device mask uses (eos emitted, or max_new reached). Returns True
        while the request stays live."""
        if self.finished:
            return False
        self.tokens.append(int(token))
        if eos_token_id is not None and int(token) == int(eos_token_id):
            self.finished = True
            self.finish_reason = "eos"
        elif len(self.tokens) >= self.max_new_tokens:
            self.finished = True
            self.finish_reason = "length"
        return not self.finished


class SchedulerConfig:
    """Engine/scheduler knobs.

    num_slots: fixed capacity of the padded active set (the decode
        quantum is compiled once for this batch).
    prefill_chunk: max prompt tokens a new arrival pushes through the
        mixed batch per step (chunked prefill keeps admission latency
        bounded while in-flight slots keep decoding).
    decode_quantum: decode steps per jitted dispatch; the host scheduler
        only runs (admit/retire) at quantum boundaries.
    """

    def __init__(self, num_slots=8, prefill_chunk=64, decode_quantum=8):
        self.num_slots = int(num_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.decode_quantum = int(decode_quantum)
        if self.num_slots < 1 or self.prefill_chunk < 1 \
                or self.decode_quantum < 1:
            raise ValueError("all SchedulerConfig knobs must be >= 1")


class Scheduler:
    """Admission queue + slot table + block reservations.

    ``companion_pools`` are additional pools every admitted request also
    occupies (the speculative engine's DRAFT KV pool); they must share
    the main pool's block size, and capacity is gated on the TIGHTEST
    pool. ``token_margin`` widens the worst-case demand by a per-request
    token slack — the speculative verify step writes up to ``gamma``
    proposal slots past the accepted history, so admission must reserve
    the blocks those writes can touch."""

    def __init__(self, config, pool, reserved_blocks=0,
                 companion_pools=(), token_margin=0):
        self.config = config
        self.pool = pool
        self.companion_pools = [p for p in companion_pools
                                if p is not None]
        for p in self.companion_pools:
            if p.block_size != pool.block_size:
                raise ValueError(
                    f"companion pool block_size {p.block_size} != main "
                    f"pool {pool.block_size}: one demand number must "
                    f"cover every pool")
        self.token_margin = int(token_margin)
        self.waiting = deque()
        self.slots = [None] * config.num_slots
        # blocks permanently unavailable to requests (engine scratch)
        self._base_reserved = int(reserved_blocks)
        self._reservations = {}  # req -> worst-case block count
        self.admitted_total = 0
        self.finished_total = 0

    # -- queue -------------------------------------------------------------
    def submit(self, request):
        if request.req_id is None:
            request.req_id = f"req{self.admitted_total + len(self.waiting)}"
        self.waiting.append(request)
        return request

    def _demand(self, req):
        return self.pool.blocks_needed(
            req.prompt_len + req.max_new_tokens + self.token_margin)

    @property
    def reserved_blocks(self):
        return self._base_reserved + sum(self._reservations.values())

    @property
    def _capacity(self):
        """Blocks the TIGHTEST pool offers — with a companion (draft)
        pool, a request only admits when it fits in every pool."""
        return min([self.pool.num_blocks]
                   + [p.num_blocks for p in self.companion_pools])

    def try_admit(self):
        """Move waiting requests into free slots while their worst-case
        block demand fits; returns the newly admitted requests (FIFO —
        a too-big head blocks the queue rather than starving)."""
        admitted = []
        while self.waiting:
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                break
            req = self.waiting[0]
            need = self._demand(req)
            if need > self._capacity - self._base_reserved:
                self.waiting.popleft()
                raise ValueError(
                    f"request {req.req_id}: needs {need} blocks, pool "
                    f"only has {self._capacity - self._base_reserved} "
                    f"usable — raise num_blocks or split the request")
            if self.reserved_blocks + need > self._capacity:
                break
            self.waiting.popleft()
            req.slot = free[0]
            self.slots[free[0]] = req
            self._reservations[req] = need
            self.admitted_total += 1
            admitted.append(req)
        return admitted

    def retire(self, req):
        """Release a finished request's slot, reservation, and pool
        blocks in EVERY pool (free-list reuse is immediate)."""
        self.pool.free(req.req_id)
        for p in self.companion_pools:
            p.free(req.req_id)
        self._reservations.pop(req, None)
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
        self.finished_total += 1

    # -- views -------------------------------------------------------------
    def live(self):
        return [r for r in self.slots if r is not None]

    def prefilling(self):
        return [r for r in self.slots if r is not None and r.prefilling]

    def decoding(self):
        return [r for r in self.slots if r is not None and r.decoding]

    @property
    def has_work(self):
        return bool(self.waiting) or any(s is not None for s in self.slots)
