"""Request state + admission scheduling for the continuous-batching
engine (reference: the serving loop around AnalysisPredictor /
``Predictor.run``'s fused_multi_transformer decode HOT LOOP — SURVEY.md
§2.6/§3.5; the scheduler itself mirrors the 2.6-era
BlockInferencePredictor's slot/block accounting — unverified, SURVEY §0).

Pure host-side bookkeeping: a priority admission queue (FIFO within a
priority class, strict priority across classes), a fixed table of
``num_slots`` serving slots (the padded active set the jitted decode
step is compiled for), and conservative block accounting against the
shared :class:`~paddle_tpu.nlp.paged_cache.PagedKVCachePool` — a request
is admitted only when its WORST-CASE block demand
(``ceil((prompt + max_new) / block_size)``) fits under the pool capacity
left unreserved by in-flight requests, so the pool can never exhaust
mid-decode. Retirement returns both the reservation and the actual
blocks (``pool.free``) for immediate reuse.

PREEMPTION (the front door's pool-pressure valve, serving/policy.py):
:meth:`Scheduler.preempt` evicts a live request — its blocks go back to
every pool (refcount-safe release), its reservation and slot are freed,
and the request re-enters the head of its priority class as a LONGER
PROMPT: resume is plain re-admission, and the re-prefill of
``prompt + tokens-so-far`` recomputes the evicted KV
(recompute-on-resume; worst-case demand is unchanged, so admission
accounting needs no new case).

PREFIX-CACHE-AWARE ADMISSION (pool ``prefix_cache=True``): admission
attaches the longest cached chain of the prefill source into the
request's tables in EVERY pool (target + draft in lockstep) and the fit
check counts only NOVEL block demand — each live request's remaining
table growth plus pending copy-on-write debt, and the candidate's
``demand - matched`` — against the free list plus what prefix eviction
can reclaim (minus the matched blocks this admission pins). With the
cache off (the default) the check reduces byte-for-byte to the static
worst-case reservation above.

TENSOR PARALLELISM: block tables, refcounts, reservations and the
admission math are indexed in BLOCKS, never bytes — and a tp-sharded
pool (nlp/paged_cache.py ``mesh=``) splits each block's kv-head axis
across chips without changing block count or identity. Every policy in
this module (priority admission, preemption, prefix-aware fit checks)
is therefore layout-invariant under ``tp>1``: the same table entry
simply addresses 1/tp of the heads on each chip, which is what keeps
prefix aliasing and COW correct on the mesh with zero scheduler
changes (the mesh-pool adversarial suite in tests/test_serving_tp.py
re-proves the refcount invariants on the sharded layout).
"""
from __future__ import annotations

from collections import deque

import numpy as np

__all__ = ["Request", "SchedulerConfig", "Scheduler"]


class Request:
    """One generation request riding the engine.

    Lifecycle: ``waiting`` (queued) -> ``prefill`` (admitted to a slot,
    prompt entering the pool chunk by chunk) -> ``decode`` (in the
    jitted quantum) -> ``finished`` (eos | stop | max_new; blocks
    freed). A PREEMPTED request cycles back to ``waiting`` with its
    emitted tokens appended to the prefill source (``begin_resume``),
    so resume is re-admission of a longer prompt.

    Per-request generation params (the front door's knobs, all applied
    at host boundaries or through existing per-slot device state):

    - ``seed``: per-slot PRNG key for the sampling arm (existing).
    - ``max_new_tokens``: per-slot retirement bound (existing).
    - ``temperature``: per-slot logits scale — requires an engine built
      with ``per_request_sampling=True`` (the per-slot temperature
      array is an input of the front-door quantum variant).
    - ``stop_token_ids`` / ``stop_sequences``: host-side stop rules
      checked as tokens are appended (``finish_reason == "stop"``; the
      device mask keeps the slot riding until the quantum boundary,
      exactly like the truncate-at-eos convention).
    - ``priority``: admission class (see serving/policy.py —
      BATCH < NORMAL < INTERACTIVE); higher admits first and may
      preempt strictly-lower classes under pool pressure.
    """

    def __init__(self, prompt, max_new_tokens=32, req_id=None, seed=0,
                 arrival_time=0.0, priority=1, temperature=None,
                 stop_token_ids=None, stop_sequences=None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.req_id = req_id
        self.seed = int(seed)
        self.arrival_time = float(arrival_time)
        self.priority = int(priority)
        self.temperature = (None if temperature is None
                            else float(temperature))
        self.stop_token_ids = frozenset(
            int(t) for t in (stop_token_ids or ()))
        self.stop_sequences = [
            [int(t) for t in s] for s in (stop_sequences or ()) if s]
        # mutable state
        self.slot = None
        self.prefill_pos = 0          # prefill tokens already in the pool
        self.prefill_target = self.prompt_len
        self._prefill_src = self.prompt
        self.cached_prefix_tokens = 0  # tokens aliased from the prefix
        # cache at this admission (prefill skips them — the TTFT win)
        self.preemptions = 0
        self.tokens: list = []        # generated token ids (incl. eos)
        self.finished = False
        self.finish_reason = None     # "eos" | "stop" | "length" |
        #   "shed" (refused admission) | "error" (quarantined/failed)
        self.admit_time = None
        self.first_token_time = None
        self.finish_time = None

    @property
    def prompt_len(self):
        return int(self.prompt.shape[0])

    @property
    def prefill_src(self):
        """The token row prefill pushes through the pool: the prompt,
        or prompt + emitted tokens after a preemption (recompute-on-
        resume re-prefills the evicted KV and the continuation token
        falls out of the final position's logits)."""
        return self._prefill_src

    @property
    def prefilling(self):
        return (self.slot is not None
                and self.prefill_pos < self.prefill_target)

    @property
    def decoding(self):
        return (self.slot is not None and not self.finished
                and self.prefill_pos >= self.prefill_target)

    def begin_resume(self):
        """Reset to the waiting state after an eviction: the next
        admission re-prefills ``prompt + tokens`` from position 0 (the
        emitted stream itself is untouched — the continuation must be
        bit-exact vs an undisturbed run)."""
        self.preemptions += 1
        self.slot = None
        self.prefill_pos = 0
        self.cached_prefix_tokens = 0  # re-admission re-attaches
        if self.tokens:
            self._prefill_src = np.concatenate(
                [self.prompt, np.asarray(self.tokens, np.int32)])
        self.prefill_target = int(self._prefill_src.shape[0])

    def _hits_stop(self):
        if self.tokens and self.tokens[-1] in self.stop_token_ids:
            return True
        for s in self.stop_sequences:
            if len(self.tokens) >= len(s) \
                    and self.tokens[-len(s):] == s:
                return True
        return False

    def record(self, token, eos_token_id=None):
        """Append one emitted token and apply the retirement rule the
        device mask uses (eos emitted, or max_new reached) plus the
        host-side per-request stop rules. Returns True while the
        request stays live."""
        if self.finished:
            return False
        self.tokens.append(int(token))
        if eos_token_id is not None and int(token) == int(eos_token_id):
            self.finished = True
            self.finish_reason = "eos"
        elif self._hits_stop():
            self.finished = True
            self.finish_reason = "stop"
        elif len(self.tokens) >= self.max_new_tokens:
            self.finished = True
            self.finish_reason = "length"
        return not self.finished


class SchedulerConfig:
    """Engine/scheduler knobs.

    num_slots: fixed capacity of the padded active set (the decode
        quantum is compiled once for this batch).
    prefill_chunk: max prompt tokens a new arrival pushes through the
        mixed batch per step (chunked prefill keeps admission latency
        bounded while in-flight slots keep decoding).
    decode_quantum: decode steps per jitted dispatch; the host scheduler
        only runs (admit/retire) at quantum boundaries.
    """

    def __init__(self, num_slots=8, prefill_chunk=64, decode_quantum=8):
        self.num_slots = int(num_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.decode_quantum = int(decode_quantum)
        if self.num_slots < 1 or self.prefill_chunk < 1 \
                or self.decode_quantum < 1:
            raise ValueError("all SchedulerConfig knobs must be >= 1")


class Scheduler:
    """Admission queue + slot table + block reservations.

    ``companion_pools`` are additional pools every admitted request also
    occupies (the speculative engine's DRAFT KV pool); they must share
    the main pool's block size, and capacity is gated on the TIGHTEST
    pool. ``token_margin`` widens the worst-case demand by a per-request
    token slack — the speculative verify step writes up to ``gamma``
    proposal slots past the accepted history, so admission must reserve
    the blocks those writes can touch."""

    def __init__(self, config, pool, reserved_blocks=0,
                 companion_pools=(), token_margin=0):
        self.config = config
        self.pool = pool
        self.companion_pools = [p for p in companion_pools
                                if p is not None]
        for p in self.companion_pools:
            if p.block_size != pool.block_size:
                raise ValueError(
                    f"companion pool block_size {p.block_size} != main "
                    f"pool {pool.block_size}: one demand number must "
                    f"cover every pool")
        self.token_margin = int(token_margin)
        # requests admitted with their whole prompt cached still owe one
        # future COW allocation per pool (the capped re-prefill of the
        # final prompt token writes into the tail shared block); the
        # dynamic fit check carries the debt until the engine clears it
        self._cow_debt = {}  # req -> blocks its pending COW may allocate
        self.waiting = deque()
        self.slots = [None] * config.num_slots
        # blocks permanently unavailable to requests (engine scratch)
        self._base_reserved = int(reserved_blocks)
        self._reservations = {}  # req -> worst-case block count
        self.admitted_total = 0
        self.finished_total = 0
        self.preempted_total = 0
        self.resumed_total = 0
        self._submitted_total = 0  # monotonic req_id source (a derived
        # id like admitted+waiting can repeat once preemption requeues)

    # -- queue -------------------------------------------------------------
    def submit(self, request):
        if request.req_id is None:
            request.req_id = f"req{self._submitted_total}"
        self._submitted_total += 1
        self.waiting.append(request)
        return request

    def _demand(self, req):
        return self.pool.blocks_needed(
            req.prompt_len + req.max_new_tokens + self.token_margin)

    # -- prefix-cache-aware admission --------------------------------------
    @property
    def _prefix_on(self):
        return getattr(self.pool, "prefix_cache_enabled", False)

    def _all_pools(self):
        return [self.pool] + self.companion_pools

    def _match_blocks(self, req):
        """Full blocks EVERY pool can alias for ``req``'s prefill source
        — the min across pools, so the draft pool attaches in LOCKSTEP
        with the target pool and the engine's shared per-slot sequence
        length stays consistent."""
        if not self._prefix_on:
            return 0
        return min(p.prefix_match_stats(req.prefill_src)["matched_blocks"]
                   for p in self._all_pools())

    def _cow_allowance(self, req, m_blocks):
        """Blocks ``req``'s pending copy-on-write may still allocate in
        each pool: 1 when the cached prefix covers the whole prefill
        source (the engine caps ``prefill_pos`` one token short, and
        re-prefilling that token COWs the tail shared block), else 0 —
        every other write lands in a fresh block by construction."""
        return 1 if (m_blocks and m_blocks * self.pool.block_size
                     >= req.prefill_target) else 0

    def clear_cow_debt(self, req):
        """The engine calls this once ``req``'s prefill completes — any
        COW its admission could trigger has happened (or never will),
        so the debt stops inflating the dynamic fit check."""
        self._cow_debt.pop(req, None)

    def _fits(self, req, need):
        """Would ``req``'s admission keep every pool exhaustion-free in
        the worst case?

        Cache OFF: the static reservation check (worst-case demand of
        every in-flight request, pre-reserved) — byte-for-byte the
        pre-prefix-cache behavior.

        Cache ON: per-pool NOVEL-demand check. Each live request can
        still allocate at most ``demand - held`` fresh blocks (its
        table only grows toward its worst case; shared blocks it
        already maps are in ``held``) plus its pending COW debt; the
        candidate allocates ``need - matched`` fresh blocks plus its
        own COW allowance. All of that must fit in what the pool can
        produce: the free list plus cached-only blocks eviction can
        reclaim — MINUS the matched evictable blocks this admission is
        about to pin (attach bumps them to refcount 2)."""
        if not self._prefix_on:
            return self.reserved_blocks + need <= self._capacity
        m = self._match_blocks(req)
        cow_new = self._cow_allowance(req, m)
        for p in self._all_pools():
            growth = sum(
                max(0, dem - p.held_blocks(r.req_id))
                for r, dem in self._reservations.items())
            debt = sum(self._cow_debt.get(r, 0)
                       for r in self._reservations)
            pinned = p.prefix_match_stats(
                req.prefill_src, max_blocks=m)["evictable"]
            avail = (p.free_blocks + p.evictable_prefix_blocks()
                     - pinned - self._base_reserved
                     + p.held_blocks("__scratch__"))
            if growth + debt + (need - m + cow_new) > avail:
                return False
        return True

    def _attach(self, req):
        """Alias the cached prefix into ``req``'s fresh tables in every
        pool (same block count everywhere — lockstep) and record how
        many prompt tokens prefill may now skip."""
        if not self._prefix_on:
            return 0
        m = self._match_blocks(req)
        cached = 0
        for p in self._all_pools():
            cached = p.attach_prefix(req.req_id, req.prefill_src,
                                     max_blocks=m)
        req.cached_prefix_tokens = int(cached)
        allowance = self._cow_allowance(req, m)
        if allowance:
            self._cow_debt[req] = allowance
        return cached

    @property
    def reserved_blocks(self):
        return self._base_reserved + sum(self._reservations.values())

    @property
    def _capacity(self):
        """Blocks the TIGHTEST pool offers — with a companion (draft)
        pool, a request only admits when it fits in every pool."""
        return min([self.pool.num_blocks]
                   + [p.num_blocks for p in self.companion_pools])

    def next_waiting(self):
        """The request admission would try next: the OLDEST request of
        the HIGHEST priority class present (stable within a class —
        FIFO per priority, strict priority across classes). None when
        the queue is empty."""
        best = None
        for r in self.waiting:
            if best is None or r.priority > best.priority:
                best = r
        return best

    def can_admit(self, req):
        """Would ``req`` be admitted right now? (a free slot exists and
        its worst-case demand fits under the live reservations) — the
        pressure signal the preemption policy keys on."""
        if not any(s is None for s in self.slots):
            return False
        return self._fits(req, self._demand(req))

    def try_admit(self):
        """Move waiting requests into free slots while their worst-case
        block demand fits; returns the newly admitted requests.
        Selection is priority-then-FIFO (``next_waiting``), and a
        too-big head BLOCKS its class and everything below rather than
        starving (no bypass: admitting a small low-priority request
        around a blocked high-priority head would invert priority)."""
        admitted = []
        while self.waiting:
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                break
            req = self.next_waiting()
            need = self._demand(req)
            if need > self._capacity - self._base_reserved:
                self.waiting.remove(req)
                raise ValueError(
                    f"request {req.req_id}: needs {need} blocks, pool "
                    f"only has {self._capacity - self._base_reserved} "
                    f"usable — raise num_blocks or split the request")
            if not self._fits(req, need):
                break
            self.waiting.remove(req)
            req.slot = free[0]
            self.slots[free[0]] = req
            self._reservations[req] = need
            self._attach(req)
            # a request with preemptions behind it was admitted before:
            # this admission is the RESUME half of a preempt/resume
            # pair, not new work
            if req.preemptions:
                self.resumed_total += 1
            else:
                self.admitted_total += 1
            admitted.append(req)
        return admitted

    def preempt(self, req):
        """Evict a LIVE request under pool pressure: release its blocks
        in every pool (refcount-safe — a shared block only returns to
        the free list when its last holder lets go), drop the
        reservation, free the slot, and requeue it at the HEAD of the
        waiting queue as a longer prompt (``Request.begin_resume``) so
        resume is plain re-admission + re-prefill."""
        if req.slot is None or req.finished:
            raise ValueError(
                f"request {req.req_id} is not live (slot={req.slot}, "
                f"finished={req.finished}): only an in-flight request "
                f"can be preempted")
        self.pool.free(req.req_id)
        for p in self.companion_pools:
            p.free(req.req_id)
        self._reservations.pop(req, None)
        self._cow_debt.pop(req, None)
        self.slots[req.slot] = None
        req.begin_resume()
        # head of the deque: the stable scan in next_waiting() puts a
        # resumed request ahead of its class (it was admitted first)
        self.waiting.appendleft(req)
        self.preempted_total += 1
        return req

    def retire(self, req):
        """Release a finished request's slot, reservation, and pool
        blocks in EVERY pool (free-list reuse is immediate)."""
        self.pool.free(req.req_id)
        for p in self.companion_pools:
            p.free(req.req_id)
        self._reservations.pop(req, None)
        self._cow_debt.pop(req, None)
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
        self.finished_total += 1

    # -- views -------------------------------------------------------------
    def live(self):
        return [r for r in self.slots if r is not None]

    def prefilling(self):
        return [r for r in self.slots if r is not None and r.prefilling]

    def decoding(self):
        return [r for r in self.slots if r is not None and r.decoding]

    @property
    def has_work(self):
        return bool(self.waiting) or any(s is not None for s in self.slots)

    def steady_state(self):
        """True when the batch composition CANNOT change before the
        next dispatch: nothing is waiting for admission, no slot is
        mid-prefill, and at least one slot is decoding. This is the
        predicate the engine's multi-quantum driver consults to decide
        how many decode quanta to run per dispatch — in steady state
        the host has no scheduling decision to make between quanta
        (retirement is handled by the on-device eos/max-len masks, and
        the admission reservation already covers every live row's
        worst-case growth), so re-entering Python between them buys
        nothing."""
        return (not self.waiting and not self.prefilling()
                and bool(self.decoding()))
