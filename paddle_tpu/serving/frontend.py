"""The async serving front door: streaming request API, priority
preemption, SLO-aware load shedding, and graceful drain over the
continuous-batching :class:`~paddle_tpu.serving.engine.ServingEngine`
(reference: the serving *system* around AnalysisPredictor /
``Predictor.run`` — PAPER.md §2.6/§3.5 — that turns the engine loop
into a product; entry point ``paddle.inference.serve()``).

What the front door adds, all as HOST-SIDE policy at the engine's
existing scheduler boundaries (the compiled quantum's
``max_host_callbacks=0`` budget and golden fingerprint are unchanged —
the ``serving_frontdoor_step`` analysis recipe pins the
per-request-sampling quantum variant with its own golden):

- **token-by-token streaming**: :meth:`ServingFrontDoor.submit`
  returns a :class:`TokenStream` — iterate it synchronously (each pull
  pumps the engine) or ``async for`` it under :meth:`run_async`; the
  engine's ``token_sink`` hook pushes every emitted token the moment
  the host sees it.
- **per-request generation params**: ``max_new_tokens`` / ``seed``
  ride the existing per-slot state; ``temperature`` rides the
  front-door quantum variant's per-slot temps input
  (``per_request_sampling=True``); ``stop_token_ids`` /
  ``stop_sequences`` are host-side stop rules (``finish_reason ==
  "stop"``, truncate-at-stop convention like eos).
- **priority preemption**: under pool pressure the pump evicts a
  strictly-lower-priority victim (policy.py's :func:`choose_victim`),
  returning its blocks to the pool (refcount-safe) and requeueing it
  for RECOMPUTE-ON-RESUME — re-admission of a longer prompt whose
  continuation is bit-exact vs an undisturbed run, with TTFT observed
  exactly once (tests/test_serving's preemption oracle).
- **SLO-aware load shedding + backpressure**: admission consults the
  burn-rate health report (``engine.health()``, cached
  ``health_interval_s``) and queue depth through
  :class:`~paddle_tpu.serving.policy.FrontDoorPolicy`; shed requests
  fire the obs ``on_shed`` hook (bad-outcome sample — the shed rate
  burns the error-rate SLO) and their flight journal captures.
- **graceful drain**: :meth:`drain` stops NEW admissions (submissions
  shed with reason ``draining``), finishes everything already
  accepted, and flushes the flight recorder.
- **failure semantics + crash recovery**: streams never hang — an
  engine-side failure or an engine gone idle closes every open stream
  terminally with ``finish_reason == "error"`` and ``timeout=`` bounds
  each token wait; :meth:`ServingFrontDoor.snapshot` /
  :meth:`ServingFrontDoor.restore` rebuild the whole front door from a
  JSON-able engine snapshot with in-flight streams re-opened and
  pre-loaded (recompute-on-resume; serving/engine.py).
- **prefix-cache visibility**: on a ``prefix_cache=True`` engine,
  ``TokenStream.cached_prefix_tokens`` reports how many prompt tokens
  this request aliased from the content-addressed prefix index
  (prefill skipped them — the shared-system-prompt TTFT win), and
  :meth:`stats` carries the engine's ``prefix_cache`` counter block.

Benched by ``scripts/bench_serving.py serving_overload`` (p95 TTFT +
shed rate under a >capacity Poisson burst, shed vs no-shed arms;
artifact BENCH_FRONTDOOR_r10.json).
"""
from __future__ import annotations

from collections import deque

import numpy as np

from .policy import NORMAL, FrontDoorPolicy, choose_victim
from .scheduler import Request

__all__ = ["TokenStream", "ServingFrontDoor"]


class TokenStream:
    """One request's streaming handle.

    Sync: ``for tok in stream`` — each pull pumps the front door until
    a token lands or the request finishes. Async: ``async for tok in
    stream`` under a running :meth:`ServingFrontDoor.run_async` task.
    ``stream.result()`` drives to completion and returns the generated
    ids as one int32 array; ``stream.request`` is the live
    :class:`~paddle_tpu.serving.scheduler.Request` (``finish_reason``:
    ``eos`` | ``stop`` | ``length`` | ``shed`` | ``error``).

    Failure semantics (the hang fix): an engine-side exception during a
    pump, or the engine going idle with this stream still open, closes
    the stream terminally with ``finish_reason == "error"`` instead of
    blocking the consumer forever; ``timeout`` seconds without a new
    token raises ``TimeoutError`` (sync) / ``asyncio.TimeoutError``
    (async) without touching the request's engine state."""

    def __init__(self, request, frontdoor, timeout=None):
        self.request = request
        self._fd = frontdoor
        self._buf = deque()
        self._closed = False
        self._timeout = None if timeout is None else float(timeout)
        self._aevent = None  # lazy: only async consumers pay for it

    # -- producer side (the front door's token sink) ----------------------
    def _push(self, tok):
        self._buf.append(int(tok))
        self._wake()

    def _close(self):
        self._closed = True
        self._wake()

    def _error_close(self, detail):
        """Terminal error close: the request is finished with
        ``finish_reason="error"`` (only if nothing finished it first)
        and the stream closes — the consumer's loop ends instead of
        hanging. Only called when the request is OUT of the engine
        (engine idle / engine dead), so the mutation cannot race a
        live slot."""
        req = self.request
        if not req.finished:
            req.finished = True
            req.finish_reason = "error"
            req.finish_time = self._fd.engine.obs.now()
        self._fd._streams.pop(str(req.req_id), None)
        self._close()

    def _wake(self):
        if self._aevent is not None:
            self._aevent.set()

    # -- consumer side -----------------------------------------------------
    @property
    def closed(self):
        return self._closed

    @property
    def shed(self):
        return self.request.finish_reason == "shed"

    @property
    def finish_reason(self):
        return self.request.finish_reason

    @property
    def cached_prefix_tokens(self):
        """Prompt tokens this request aliased from the prefix cache at
        its latest admission (0 on an unshared engine or a cache miss):
        tokens that paid NO prefill compute and no fresh pool
        residency — the per-request view of the shared-system-prompt
        TTFT win."""
        return self.request.cached_prefix_tokens

    def __iter__(self):
        eng = self._fd.engine
        last = eng.obs.now()
        while True:
            while self._buf:
                last = eng.obs.now()
                yield self._buf.popleft()
            if self._closed:
                return
            if not eng.has_work:
                # the engine went idle while this stream is still open:
                # the request fell out of the scheduler (engine died or
                # dropped it) — pumping again would spin forever
                self._error_close("engine idle with stream open")
                return
            if (self._timeout is not None
                    and eng.obs.now() - last > self._timeout):
                raise TimeoutError(
                    f"no token for request {self.request.req_id!r} in "
                    f"{self._timeout}s")
            try:
                self._fd.pump()
            except Exception:
                # engine-side failure: every open stream (this one
                # included) closes with finish_reason="error"; the
                # pumping caller also sees the exception
                self._fd._fail_open_streams()
                raise

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio

        while True:
            if self._buf:
                return self._buf.popleft()
            if self._closed:
                raise StopAsyncIteration
            if self.request.finished:
                # finished without a closing push (e.g. quarantined
                # with finish_reason="error") — terminal, not a hang
                self._close()
                raise StopAsyncIteration
            if self._aevent is None:
                self._aevent = asyncio.Event()
            if self._timeout is None:
                await self._aevent.wait()
            else:
                await asyncio.wait_for(self._aevent.wait(),
                                       self._timeout)
            self._aevent.clear()

    def result(self):
        """Drain this stream to completion (pumping as needed) and
        return the full generated id row as int32."""
        for _ in self:
            pass
        return np.asarray(self.request.tokens, np.int32)


class ServingFrontDoor:
    """The serving system around one engine: submissions pass the
    shedding policy, the pump applies preemption before every scheduler
    iteration, and every emitted token streams out through
    :class:`TokenStream`.

    Args:
        engine: a :class:`~paddle_tpu.serving.engine.ServingEngine`
            (build with ``slo=`` for health-driven shedding and
            ``flight=`` for drain-flushable journals;
            ``paddle.inference.serve()`` wires the stock setup).
        policy: a :class:`~paddle_tpu.serving.policy.FrontDoorPolicy`
            (default: stock ladder — shed BATCH at warn, BATCH+NORMAL
            at critical, preemption on).
    """

    def __init__(self, engine, policy=None):
        self.engine = engine
        self.policy = policy if policy is not None else FrontDoorPolicy()
        if engine.token_sink is not None:
            raise ValueError(
                "engine already has a token_sink — one front door per "
                "engine")
        engine.token_sink = self._on_token
        self._streams = {}       # req_id -> TokenStream
        self.shed_requests = []  # Request handles refused admission
        self._shed_seq = 0
        self._draining = False
        self._stopped = False
        self._health = ("ok", float("-inf"))  # (state, stamped at)

    # -- submission --------------------------------------------------------
    def submit(self, prompt, max_new_tokens=32, priority=NORMAL,
               temperature=None, stop_token_ids=None,
               stop_sequences=None, seed=0, req_id=None, timeout=None):
        """Admit-or-shed one request; always returns a
        :class:`TokenStream` (a shed request's stream is already closed
        with ``finish_reason == "shed"`` — check ``stream.shed``).
        ``timeout`` bounds the stream's wait for each next token
        (None = wait forever; see :class:`TokenStream`)."""
        eng = self.engine
        now = eng.obs.now()
        if self._draining:
            return self._shed(prompt, max_new_tokens, priority, seed,
                              req_id, now, reason="draining")
        admit, reason = self.policy.admission(
            priority, self._health_state(now),
            waiting_depth=len(eng.scheduler.waiting))
        if not admit:
            return self._shed(prompt, max_new_tokens, priority, seed,
                              req_id, now, reason=reason)
        req = eng.submit(prompt, max_new_tokens=max_new_tokens,
                         req_id=req_id, seed=seed, priority=priority,
                         temperature=temperature,
                         stop_token_ids=stop_token_ids,
                         stop_sequences=stop_sequences,
                         arrival_time=now)
        stream = TokenStream(req, self, timeout=timeout)
        self._streams[str(req.req_id)] = stream
        return stream

    def _shed(self, prompt, max_new_tokens, priority, seed, req_id,
              now, reason):
        """Refuse one submission: the request never touches the
        scheduler; obs records the bad-outcome sample (the shed rate
        burns the error-rate SLO) and the flight recorder captures the
        (short) journal — shedding IS an anomaly."""
        eng = self.engine
        if req_id is None:
            req_id = f"shed{self._shed_seq}"
        self._shed_seq += 1
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      req_id=req_id, seed=seed, priority=priority,
                      arrival_time=now)
        req.finished = True
        req.finish_reason = "shed"
        req.finish_time = now
        if eng.flight is not None:
            eng.flight.on_submit(req, now)
            eng.flight.on_shed(req, now, reason=reason)
        eng.obs.on_shed(req, now)
        self.shed_requests.append(req)
        stream = TokenStream(req, self)
        stream._close()
        return stream

    def _health_state(self, now):
        """The engine's burn-rate health state, re-evaluated at most
        every ``policy.health_interval_s`` (no SLOs attached -> always
        ``ok``: shedding then rests on backpressure alone)."""
        if self.engine.slo is None:
            return "ok"
        state, stamped = self._health
        if now - stamped < self.policy.health_interval_s:
            return state
        state = self.engine.health(now=now)["state"]
        self._health = (state, now)
        return state

    # -- the pump ----------------------------------------------------------
    def _on_token(self, req, tok):
        stream = self._streams.get(str(req.req_id))
        if stream is None:
            return
        stream._push(tok)
        if req.finished:
            stream._close()
            self._streams.pop(str(req.req_id), None)

    def _apply_preemption(self):
        """Before admitting: if the highest-priority waiting request
        cannot fit, evict strictly-lower-priority victims until it can
        (or no victim remains). Equal priority never preempts — no
        thrash between peers — and a resumed victim can itself only be
        preempted again by a strictly higher class."""
        if not self.policy.preempt:
            return 0
        sched = self.engine.scheduler
        head = sched.next_waiting()
        if head is None:
            return 0
        n = 0
        while (n < self.policy.max_preemptions_per_pump
                and not sched.can_admit(head)):
            victim = choose_victim(sched.live(), head.priority)
            if victim is None:
                break
            self.engine.preempt(victim)
            n += 1
        return n

    def _reap_finished(self):
        """Close streams whose request finished WITHOUT a final token
        push: a quarantined (``finish_reason="error"``) request emits
        nothing, so ``_on_token`` never fires for it — without this
        sweep its consumer would pump forever."""
        for rid, stream in list(self._streams.items()):
            if stream.request.finished:
                stream._close()
                self._streams.pop(rid, None)

    def _fail_open_streams(self):
        """The engine raised out of a pump: every open stream closes
        terminally with ``finish_reason="error"`` so no consumer —
        including ones on other streams — blocks on a dead engine."""
        for stream in list(self._streams.values()):
            stream._error_close("engine failed")
        self._streams.clear()

    def pump(self):
        """One front-door iteration: preemption policy, then one engine
        scheduler step (admit -> mixed prefill | decode quantum ->
        retire), then the finished-stream reap. Returns True while work
        remains."""
        self._apply_preemption()
        alive = self.engine.step()
        self._reap_finished()
        return alive

    def pump_dispatch(self):
        """DISPATCH HALF of :meth:`pump` — preemption policy + the
        engine's async :meth:`~ServingEngine.step_dispatch`. Returns
        the opaque pending record for :meth:`pump_collect`. The cluster
        front door drives every replica's dispatch half before any
        collect half, so no replica's host work serializes on another
        replica's device wall; ``pump()`` is equivalent to
        ``pump_collect(pump_dispatch())`` (it goes through
        ``engine.step()`` — the composition of the same two halves — so
        wrappers around ``step`` still see every pump)."""
        self._apply_preemption()
        return self.engine.step_dispatch()

    def pump_collect(self, pending):
        """COLLECT HALF of :meth:`pump`: force the pending dispatch,
        reap finished streams, report whether work remains."""
        alive = self.engine.step_collect(pending)
        self._reap_finished()
        return alive

    def run_until_idle(self):
        """Drive synchronously until no work remains; returns the
        engine's completed-request list."""
        while self.engine.has_work:
            self.pump()
        return self.engine.completed

    async def run_async(self, idle_s=0.001):
        """The serving loop as a coroutine: pump while work exists
        (yielding to the event loop between dispatches so streaming
        consumers run), sleep briefly when idle, exit on :meth:`stop`
        or when a drain completes."""
        import asyncio

        self._stopped = False
        while not self._stopped:
            if self.engine.has_work:
                self.pump()
                await asyncio.sleep(0)
            elif self._draining:
                break
            else:
                await asyncio.sleep(idle_s)

    def stop(self):
        """Stop :meth:`run_async` after its current iteration (no
        drain: queued work stays queued)."""
        self._stopped = True

    # -- drain -------------------------------------------------------------
    def drain(self, flight_path=None):
        """Graceful drain: stop accepting NEW submissions (they shed
        with reason ``draining``), finish everything already accepted
        — queued and in-flight — then flush the flight recorder
        (optionally to ``flight_path`` as JSONL). Returns a summary
        dict; the front door stays drained (build a new one to
        serve again)."""
        eng = self.engine
        if not self._draining:
            self._draining = True
            eng.obs.on_drain(eng.obs.now(),
                             live=len(eng.scheduler.live()),
                             waiting=len(eng.scheduler.waiting))
        while eng.has_work:
            self.pump()
        out = {
            "drained": True,
            "completed": len(eng.completed),
            "shed": len(self.shed_requests),
            "preempted": eng.scheduler.preempted_total,
            "resumed": eng.scheduler.resumed_total,
        }
        if eng.flight is not None:
            out["flight"] = eng.flight.stats()
            if flight_path is not None:
                out["flight_path"] = eng.flight.save(flight_path)
        return out

    # -- crash recovery ----------------------------------------------------
    def snapshot(self):
        """The engine's crash-recovery snapshot (JSON-able; see
        :meth:`ServingEngine.snapshot`) — the front door adds nothing:
        its streams are reconstructed by :meth:`restore`."""
        return self.engine.snapshot()

    @classmethod
    def restore(cls, snap, model, policy=None, spec_draft=None,
                **overrides):
        """Rebuild a front door (and its engine) from a snapshot: every
        in-flight request is re-admitted via recompute-on-resume and
        gets a FRESH open :class:`TokenStream` pre-loaded with its
        already-emitted tokens — a consumer iterating the restored
        stream sees the full sequence, and the continuation is
        bit-exact for greedy requests."""
        from .engine import ServingEngine

        eng = ServingEngine.restore(snap, model, spec_draft=spec_draft,
                                    **overrides)
        fd = cls(eng, policy=policy)
        for req in list(eng.scheduler.waiting):
            stream = TokenStream(req, fd)
            for tok in req.tokens:
                stream._buf.append(int(tok))
            fd._streams[str(req.req_id)] = stream
        return fd

    # -- views -------------------------------------------------------------
    @property
    def draining(self):
        return self._draining

    def stats(self):
        """Front-door counters merged over the engine's: shed /
        preempted / resumed / drain state next to the engine stats."""
        out = self.engine.engine_stats()
        out["shed"] = len(self.shed_requests)
        out["draining"] = self._draining
        out["open_streams"] = len(self._streams)
        return out
