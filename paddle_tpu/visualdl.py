"""VisualDL-compatible LogWriter (reference: the VisualDL package the
reference ecosystem logs to — unverified, SURVEY.md §0/§5 observability
row).

Zero-dependency storage: one append-only JSONL stream per writer
(``vdlrecords.<ts>.jsonl``) with {tag, step, value, wall_time} records —
greppable, pandas-loadable, and streamable while training. The reader
(``LogReader``) restores per-tag scalar series for tooling/tests.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

__all__ = ["LogWriter", "LogReader"]


class LogWriter:
    """``with LogWriter(logdir='./runs') as w: w.add_scalar(...)``"""

    def __init__(self, logdir="./vdl_log", file_name=None, **kwargs):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        if file_name is None:
            file_name = f"vdlrecords.{int(time.time() * 1000)}.jsonl"
        self._path = os.path.join(logdir, file_name)
        self._f = open(self._path, "a")

    @property
    def file_path(self):
        return self._path

    def _write(self, kind, tag, step, payload):
        rec = {"kind": kind, "tag": tag, "step": int(step),
               "wall_time": time.time()}
        rec.update(payload)
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def add_scalar(self, tag, value, step, walltime=None):
        self._write("scalar", tag, step, {"value": float(value)})

    def add_histogram(self, tag, values, step, buckets=10):
        arr = np.asarray(values).reshape(-1)
        if arr.size == 0:
            self._write("histogram", tag, step, {
                "hist": [], "edges": [], "min": 0.0, "max": 0.0, "mean": 0.0,
            })
            return
        hist, edges = np.histogram(arr, bins=buckets)
        self._write("histogram", tag, step, {
            "hist": hist.tolist(), "edges": edges.tolist(),
            "min": float(arr.min()), "max": float(arr.max()),
            "mean": float(arr.mean()),
        })

    def add_text(self, tag, text_string, step):
        self._write("text", tag, step, {"text": str(text_string)})

    def add_hparams(self, hparams_dict, metrics_list=None, **kwargs):
        self._write("hparams", "hparams", 0, {
            "hparams": {k: str(v) for k, v in hparams_dict.items()},
            "metrics": list(metrics_list or []),
        })

    def flush(self):
        self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class LogReader:
    """Reads every vdlrecords JSONL stream under ``logdir``."""

    def __init__(self, logdir):
        self.logdir = logdir

    def _records(self):
        for name in sorted(os.listdir(self.logdir)):
            if not name.startswith("vdlrecords."):
                continue
            with open(os.path.join(self.logdir, name)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield json.loads(line)

    def tags(self, kind="scalar"):
        return sorted({
            r["tag"] for r in self._records() if r["kind"] == kind
        })

    def scalars(self, tag):
        return [
            (r["step"], r["value"])
            for r in self._records()
            if r["kind"] == "scalar" and r["tag"] == tag
        ]
