"""paddle.metric (reference: python/paddle/metric/metrics.py — unverified,
SURVEY.md §0)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy."""
    from ..tensor.search import topk as _topk
    import jax.numpy as jnp
    from ..core.dispatch import apply

    input = input if isinstance(input, Tensor) else Tensor(input)
    label = label if isinstance(label, Tensor) else Tensor(label)

    def fn(logits, lab):
        _, pred = __import__("jax").lax.top_k(logits, k)
        if lab.ndim == logits.ndim:
            lab_ = lab
        else:
            lab_ = lab.reshape(lab.shape + (1,))
        hit = jnp.any(pred == lab_, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply(fn, input, label, op_name="accuracy")


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = pred if isinstance(pred, Tensor) else Tensor(pred)
        label = label if isinstance(label, Tensor) else Tensor(label)
        import jax
        import jax.numpy as jnp
        from ..core.dispatch import apply

        maxk = self.maxk

        def fn(logits, lab):
            _, top = jax.lax.top_k(logits, maxk)
            if lab.ndim == 1:
                lab_ = lab[:, None]
            else:
                lab_ = lab
            return (top == lab_).astype(jnp.float32)

        return apply(fn, pred, label, op_name="acc_compute")

    def update(self, correct, *args):
        arr = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        num_samples = arr.shape[0]
        accs = []
        for k in self.topk:
            num_corrects = arr[:, :k].sum()
            self.total[self.topk.index(k)] += num_corrects
            self.count[self.topk.index(k)] += num_samples
            accs.append(float(num_corrects) / num_samples)
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [
            t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)
        ]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        labels = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        pred_pos = (preds.reshape(-1) > 0.5).astype(np.int32)
        lab = labels.reshape(-1).astype(np.int32)
        self.tp += int(((pred_pos == 1) & (lab == 1)).sum())
        self.fp += int(((pred_pos == 1) & (lab == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        labels = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        pred_pos = (preds.reshape(-1) > 0.5).astype(np.int32)
        lab = labels.reshape(-1).astype(np.int32)
        self.tp += int(((pred_pos == 1) & (lab == 1)).sum())
        self.fn += int(((pred_pos == 0) & (lab == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        labels = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        lab = labels.reshape(-1)
        bins = np.clip(
            (pos_prob * self.num_thresholds).astype(np.int64), 0,
            self.num_thresholds,
        )
        for b, l in zip(bins, lab):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name
