"""Shared helpers for the Pallas kernel tier."""
from __future__ import annotations

import jax


def interpret_mode():
    """Pallas kernels run in interpret mode off-TPU (CPU test suite)."""
    return jax.default_backend() != "tpu"


def round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m
