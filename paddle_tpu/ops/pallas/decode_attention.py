"""KV-cache decode attention — Pallas TPU kernel.

The heart of the reference's ``fused_multi_transformer`` inference op
(paddle/fluid/operators/fused/fused_multi_transformer_op.cu — unverified,
SURVEY.md §0/§2.5): one query step attends over a pre-filled KV cache with
per-batch valid lengths.

Layout choices for the MXU: all query heads sharing one KV head (the GQA
group) are processed together as the rows of the score matmul, so a
7B-class decode (32 q heads / 8 kv heads → G=4) still issues (G, D) x
(D, BK) matmuls instead of degenerate single-row ones. Per-batch lengths
ride in scalar-prefetch SMEM.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode as _interpret_mode, round_up as _round_up

DEFAULT_BLOCK_K = 256
NEG_INF = -1e30




def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, sm_scale, block_k, kv_steps,
                   group):
    b = pl.program_id(0)
    ki = pl.program_id(2)
    length = lens_ref[b]

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(ki * block_k < length)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)  # (G, D)
        k = k_ref[0, 0].astype(jnp.float32)  # (BK, D)
        v = v_ref[0, 0].astype(jnp.float32)  # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (G, BK)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (group, block_k), 1
        )
        mask = k_pos < length
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(ki == kv_steps - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, seq_lens, sm_scale=None,
                     block_k=DEFAULT_BLOCK_K):
    """One-step decode attention over a KV cache.

    Args:
        q: (B, H, D) or (B, 1, H, D) — the new token's query heads.
        k_cache, v_cache: (B, S_max, HK, D) paddle cache layout. HK may be
            smaller than H (GQA/MQA) as long as H % HK == 0.
        seq_lens: (B,) int32 — valid cache entries per batch row
            (including the token being decoded, already written).
    Returns (B, H, D) (or (B, 1, H, D) matching q's rank).
    """
    squeeze = False
    if q.ndim == 4:
        q = q[:, 0]
        squeeze = True
    b, h, d = q.shape
    s_max, hk = k_cache.shape[1], k_cache.shape[2]
    if h % hk != 0:
        raise ValueError(f"query heads ({h}) must be a multiple of kv heads ({hk})")
    group = h // hk
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    # (B, HK, G, D) queries; (B, HK, S, D) caches
    qg = q.reshape(b, hk, group, d)
    kt = jnp.swapaxes(k_cache, 1, 2)
    vt = jnp.swapaxes(v_cache, 1, 2)
    block_k = min(block_k, ((s_max + 7) // 8) * 8)
    pad_k = (-s_max) % block_k
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    kv_steps = pl.cdiv(s_max + pad_k, block_k)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hk, kv_steps),
        in_specs=[
            pl.BlockSpec((1, 1, group, d), lambda b_, h_, ki, lens: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, lens: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, lens: (b_, h_, ki, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, d), lambda b_, h_, ki, lens: (b_, h_, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, sm_scale=sm_scale, block_k=block_k,
            kv_steps=kv_steps, group=group,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, group, d), q.dtype),
        interpret=_interpret_mode(),
    )(seq_lens.astype(jnp.int32), qg, kt, vt)
    out = out.reshape(b, h, d)
    return out[:, None] if squeeze else out
