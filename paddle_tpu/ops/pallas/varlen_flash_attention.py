"""Varlen (packed / unpadded) flash attention — Pallas TPU kernel.

Replaces the reference's varlen path through its vendored flash-attn
library (reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu
`FlashAttnUnpaddedKernel` + third_party/flashattn — unverified,
SURVEY.md §0/§2.5): sequences are packed back-to-back into one
(total_tokens, heads, head_dim) buffer with `cu_seqlens` prefix sums,
and attention never crosses sequence boundaries.

TPU-first design (splash-attention structure, not a CUDA port):
- Tile predicates are precomputed in XLA from cu_seqlens and fed to the
  kernel via scalar prefetch (SMEM): `run[qi, ki]` (segment ranges
  overlap, and for causal some aligned pair is on/below the diagonal)
  and `full[qi, ki]` (every pair valid → mask-free MXU fast path).
  Dead tiles skip their KV DMA entirely — the BlockSpec index map
  consults `run` and re-points at block 0 — so compute AND bandwidth
  scale with O(sum len_i^2), not O(T^2).
- Partial (boundary) tiles mask via per-token int32 segment ids and
  bottom-right-aligned relative positions, streamed in Mosaic-friendly
  layouts: q-side (T, 128) broadcast along lanes, kv-side (8, T)
  broadcast along sublanes (the same trick jax's own flash kernel uses
  for segment ids).
- Unequal q/kv lengths per sequence use bottom-right causal alignment
  via the relative positions (the dense kernel's convention).
- GQA/MQA: the shared KV head is read zero-copy through the BlockSpec
  index map; only the dk/dv kernel sees KV repeated per query head.

Forward + recompute backward (dq and dk/dv kernels) under
``jax.custom_vjp``; integer aux arrays get ``None`` cotangents.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode as _interpret_mode, round_up as _round_up

NEG_INF = -1e30
LANES = 128       # minor-dim tile for the q-side aux arrays
SUBLANES = 8      # second-minor tile for the kv-side aux arrays
_Q_PAD_SEG = -1   # padding segment ids chosen so q-pad never equals
_K_PAD_SEG = -2   # k-pad (and neither equals a real id >= 0)
_REL_LO = -(2 ** 30)
_REL_HI = 2 ** 30


def _default_blocks(head_dim):
    """(1024, 1024) matches the dense kernel since round 5: keeping the
    matmul operands in their storage dtype (bf16) freed the VMEM the old
    f32 tile copies consumed, so the dkv backward now fits at 1024 with
    the segment/relative aux tiles (measured: fwd 1.47x, fwd+bwd 1.22x
    over the old 512 ceiling on the round-3 ragged-16k workload;
    (2048, 1024) still exceeds v5e's 16 MB scoped VMEM)."""
    if head_dim <= 128:
        return 1024, 1024
    return 256, 256


def _partial_mask(qs_ref, qr_ref, ks_ref, kr_ref, causal, block_k,
                  window=None):
    """(BQ, BK) validity mask for a boundary tile."""
    reps = block_k // LANES
    qs_t = jnp.tile(qs_ref[...], (1, reps))   # (BQ, BK)
    mask = qs_t == ks_ref[0:1, :]
    if causal:
        qr_t = jnp.tile(qr_ref[...], (1, reps))
        mask = mask & (qr_t >= kr_ref[0:1, :])
        if window is not None:
            # sliding-window band in per-segment relative coordinates
            mask = mask & (kr_ref[0:1, :] > qr_t - window)
    return mask


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------
def _fwd_kernel(run_ref, full_ref, q_ref, k_ref, v_ref,
                qs_ref, qr_ref, ks_ref, kr_ref,
                o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                causal, sm_scale, block_k, kv_steps, window=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = run_ref[qi, ki] == 1
    full = full_ref[qi, ki] == 1

    def accumulate(s, mask):
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        # storage-dtype matmul inputs + f32 accumulation (round-5: an
        # .astype(f32) on the operands forces quarter-rate f32 MXU)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    def scores():
        return jax.lax.dot_general(
            q_ref[0], k_ref[0],
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale

    @pl.when(run & full)
    def _interior():  # mask-free fast path
        accumulate(scores(), None)

    @pl.when(run & ~full)
    def _boundary():
        mask = _partial_mask(qs_ref, qr_ref, ks_ref, kr_ref, causal, block_k, window)
        accumulate(scores(), mask)

    @pl.when(ki == kv_steps - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _varlen_fwd(q, k, v, qs, qr, ks, kr, run_map, full_map,
                causal, sm_scale, block_q, block_k, window=None):
    """q: (H, Tq, D); k/v: (HK, Tk, D); aux pre-padded to block multiples."""
    h, tq, d = q.shape
    hk, tk = k.shape[0], k.shape[1]
    group = h // hk
    q_steps = pl.cdiv(tq, block_q)
    kv_steps = pl.cdiv(tk, block_k)

    def kv_idx(h_, qi, ki, run_ref, full_ref):
        # dead tile → re-point at block 0: Mosaic elides the repeated DMA
        return (h_ // group, jax.lax.select(run_ref[qi, ki] == 1, ki, 0), 0)

    def kv_aux_idx(h_, qi, ki, run_ref, full_ref):
        live = (run_ref[qi, ki] == 1) & (full_ref[qi, ki] == 0)
        return (0, jax.lax.select(live, ki, 0))

    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=sm_scale,
        block_k=block_k, kv_steps=kv_steps, window=window,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(h, q_steps, kv_steps),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda h_, qi, ki, r, f: (h_, qi, 0)),
                pl.BlockSpec((1, block_k, d), kv_idx),
                pl.BlockSpec((1, block_k, d), kv_idx),
                pl.BlockSpec((block_q, LANES),
                             lambda h_, qi, ki, r, f: (qi, 0)),
                pl.BlockSpec((block_q, LANES),
                             lambda h_, qi, ki, r, f: (qi, 0)),
                pl.BlockSpec((SUBLANES, block_k), kv_aux_idx),
                pl.BlockSpec((SUBLANES, block_k), kv_aux_idx),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda h_, qi, ki, r, f: (h_, qi, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda h_, qi, ki, r, f: (h_, qi, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((h, tq, 1), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(run_map, full_map, q, k, v, qs, qr, ks, kr)
    return out, lse


# --------------------------------------------------------------------------
# backward: dq kernel
# --------------------------------------------------------------------------
def _bwd_dq_kernel(run_ref, full_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, qs_ref, qr_ref, ks_ref, kr_ref,
                   dq_ref, dq_scr, *, causal, sm_scale, block_k, kv_steps,
                   window=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = run_ref[qi, ki] == 1
    full = full_ref[qi, ki] == 1

    def body(mask):
        # storage-dtype matmul inputs + f32 accumulation (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        p = jnp.exp(s - lse_ref[0])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0]) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )

    @pl.when(run & full)
    def _interior():
        body(None)

    @pl.when(run & ~full)
    def _boundary():
        body(_partial_mask(qs_ref, qr_ref, ks_ref, kr_ref, causal, block_k, window))

    @pl.when(ki == kv_steps - 1)
    def _store():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


# --------------------------------------------------------------------------
# backward: dk/dv kernel (grid over kv blocks, scan q blocks)
# --------------------------------------------------------------------------
def _bwd_dkv_kernel(run_ref, full_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, qs_ref, qr_ref, ks_ref, kr_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *,
                    causal, sm_scale, block_k, q_steps, window=None):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = run_ref[qi, ki] == 1
    full = full_ref[qi, ki] == 1

    def body(mask):
        # storage-dtype matmul inputs + f32 accumulation (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        p = jnp.exp(s - lse_ref[0])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0]) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )

    @pl.when(run & full)
    def _interior():
        body(None)

    @pl.when(run & ~full)
    def _boundary():
        body(_partial_mask(qs_ref, qr_ref, ks_ref, kr_ref, causal, block_k, window))

    @pl.when(qi == q_steps - 1)
    def _store():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _varlen_bwd(causal, sm_scale, block_q, block_k, window, residuals, g):
    q, k, v, qs, qr, ks, kr, run_map, full_map, out, lse = residuals
    do = g[0] if isinstance(g, tuple) else g
    h, tq, d = q.shape
    hk, tk = k.shape[0], k.shape[1]
    group = h // hk
    q_steps = pl.cdiv(tq, block_q)
    kv_steps = pl.cdiv(tk, block_k)

    if group > 1:
        k_r = jnp.repeat(k, group, axis=0)
        v_r = jnp.repeat(v, group, axis=0)
    else:
        k_r, v_r = k, v

    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )

    common = dict(causal=causal, sm_scale=sm_scale, block_k=block_k,
                  window=window)

    def kv_idx(h_, qi, ki, run_ref, full_ref):
        return (h_ // group, jax.lax.select(run_ref[qi, ki] == 1, ki, 0), 0)

    def kv_aux_idx(h_, qi, ki, run_ref, full_ref):
        live = (run_ref[qi, ki] == 1) & (full_ref[qi, ki] == 0)
        return (0, jax.lax.select(live, ki, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, kv_steps=kv_steps, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(h, q_steps, kv_steps),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda h_, qi, ki, r, f: (h_, qi, 0)),
                pl.BlockSpec((1, block_k, d), kv_idx),
                pl.BlockSpec((1, block_k, d), kv_idx),
                pl.BlockSpec((1, block_q, d),
                             lambda h_, qi, ki, r, f: (h_, qi, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda h_, qi, ki, r, f: (h_, qi, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda h_, qi, ki, r, f: (h_, qi, 0)),
                pl.BlockSpec((block_q, LANES),
                             lambda h_, qi, ki, r, f: (qi, 0)),
                pl.BlockSpec((block_q, LANES),
                             lambda h_, qi, ki, r, f: (qi, 0)),
                pl.BlockSpec((SUBLANES, block_k), kv_aux_idx),
                pl.BlockSpec((SUBLANES, block_k), kv_aux_idx),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, d), lambda h_, qi, ki, r, f: (h_, qi, 0)
            ),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((h, tq, d), q.dtype),
        interpret=_interpret_mode(),
    )(run_map, full_map, q, k, v, do, lse, delta, qs, qr, ks, kr)

    # dkv: grid (h, ki, qi); dead tiles skip the q-side DMAs instead
    def q_idx(h_, ki, qi, run_ref, full_ref):
        return (h_, jax.lax.select(run_ref[qi, ki] == 1, qi, 0), 0)

    def q_aux_idx(h_, ki, qi, run_ref, full_ref):
        live = (run_ref[qi, ki] == 1) & (full_ref[qi, ki] == 0)
        return (jax.lax.select(live, qi, 0), 0)

    dk_r, dv_r = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, q_steps=q_steps, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(h, kv_steps, q_steps),
            in_specs=[
                pl.BlockSpec((1, block_q, d), q_idx),
                pl.BlockSpec((1, block_k, d),
                             lambda h_, ki, qi, r, f: (h_, ki, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda h_, ki, qi, r, f: (h_, ki, 0)),
                pl.BlockSpec((1, block_q, d), q_idx),
                pl.BlockSpec((1, block_q, 1), q_idx),
                pl.BlockSpec((1, block_q, 1), q_idx),
                pl.BlockSpec((block_q, LANES), q_aux_idx),
                pl.BlockSpec((block_q, LANES), q_aux_idx),
                pl.BlockSpec((SUBLANES, block_k),
                             lambda h_, ki, qi, r, f: (0, ki)),
                pl.BlockSpec((SUBLANES, block_k),
                             lambda h_, ki, qi, r, f: (0, ki)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d),
                             lambda h_, ki, qi, r, f: (h_, ki, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda h_, ki, qi, r, f: (h_, ki, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((h, tk, d), v.dtype),
        ],
        interpret=_interpret_mode(),
    )(run_map, full_map, q, k_r, v_r, do, lse, delta, qs, qr, ks, kr)

    if group > 1:
        dk = dk_r.reshape(hk, group, tk, d).sum(axis=1).astype(k.dtype)
        dv = dv_r.reshape(hk, group, tk, d).sum(axis=1).astype(v.dtype)
    else:
        dk, dv = dk_r, dv_r
    return dq, dk, dv, None, None, None, None, None, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13))
def _varlen_htd(q, k, v, qs, qr, ks, kr, run_map, full_map,
                causal, sm_scale, block_q, block_k, window=None):
    out, _ = _varlen_fwd(q, k, v, qs, qr, ks, kr, run_map, full_map,
                         causal, sm_scale, block_q, block_k, window)
    return out


def _fwd_rule(q, k, v, qs, qr, ks, kr, run_map, full_map,
              causal, sm_scale, block_q, block_k, window=None):
    out, lse = _varlen_fwd(q, k, v, qs, qr, ks, kr, run_map, full_map,
                           causal, sm_scale, block_q, block_k, window)
    return out, (q, k, v, qs, qr, ks, kr, run_map, full_map, out, lse)


def _bwd_rule(causal, sm_scale, block_q, block_k, window, residuals, g):
    return _varlen_bwd(causal, sm_scale, block_q, block_k, window,
                       residuals, g)


_varlen_htd.defvjp(_fwd_rule, _bwd_rule)


def _aux_arrays(cu, pad_total, pad_seg, pad_rel, cu_other=None):
    """Per-token segment id and relative position from a prefix-sum.

    For the q side pass ``cu_other=cu_seqlens_k``: relative positions are
    then expressed in kv coordinates with bottom-right alignment
    (``pos - start_q + len_k - len_q``), so ``rel_q >= rel_k`` is exactly
    the dense kernel's ``tril(k=sk-sq)`` convention per segment."""
    pos = jnp.arange(pad_total, dtype=jnp.int32)
    seg = jnp.searchsorted(cu[1:], pos, side="right").astype(jnp.int32)
    n_seg = cu.shape[0] - 1
    seg_c = jnp.clip(seg, 0, n_seg - 1)
    start = cu[seg_c]
    rel = pos - start
    if cu_other is not None:
        l_own = cu[seg_c + 1] - start
        l_other = cu_other[seg_c + 1] - cu_other[seg_c]
        rel = rel + l_other - l_own
    valid = pos < cu[n_seg]
    seg = jnp.where(valid, seg, pad_seg)
    rel = jnp.where(valid, rel, pad_rel)
    return seg, rel


def _block_stats(x, steps, block):
    """Per-block (min, max) of a padded per-token int32 array."""
    xb = x.reshape(steps, block)
    return xb.min(axis=1), xb.max(axis=1)


def _tile_maps(seg_q, rel_q, seg_k, rel_k, bq, bk, causal, window=None):
    """(q_steps, kv_steps) int32 run/full predicates from per-token aux."""
    q_steps = seg_q.shape[0] // bq
    kv_steps = seg_k.shape[0] // bk
    qs_lo, qs_hi = _block_stats(seg_q, q_steps, bq)
    ks_lo, ks_hi = _block_stats(seg_k, kv_steps, bk)
    qr_lo, qr_hi = _block_stats(rel_q, q_steps, bq)
    kr_lo, kr_hi = _block_stats(rel_k, kv_steps, bk)

    run = (ks_lo[None, :] <= qs_hi[:, None]) & (
        ks_hi[None, :] >= qs_lo[:, None])
    # any real token at all (an all-pad q block has hi = _Q_PAD_SEG)
    run = run & (qs_hi[:, None] >= 0) & (ks_hi[None, :] >= 0)
    full = (
        (qs_lo[:, None] == qs_hi[:, None])
        & (ks_lo[None, :] == ks_hi[None, :])
        & (qs_lo[:, None] == ks_lo[None, :])
        & (qs_lo[:, None] >= 0)
    )
    if causal:
        run = run & (kr_lo[None, :] <= qr_hi[:, None])
        full = full & (qr_lo[:, None] >= kr_hi[None, :])
        if window is not None:
            # band lower edge (per-segment relative coords): some pair
            # within window → run; every pair within window → full
            run = run & (kr_hi[None, :] > qr_lo[:, None] - window)
            full = full & (kr_lo[None, :] > qr_hi[:, None] - window)
    return run.astype(jnp.int32), full.astype(jnp.int32)


def varlen_flash_attention(q, k, v, cu_seqlens_q, cu_seqlens_k,
                           causal=False, sm_scale=None,
                           block_q=None, block_k=None, window_size=None):
    """Packed varlen flash attention.

    q: (total_q, H, D); k/v: (total_k, HK, D); cu_seqlens_*: (B+1,) int32
    prefix sums. Tokens of sequence i occupy rows cu[i]:cu[i+1]; attention
    never crosses sequence boundaries. Returns (total_q, H, D).
    ``window_size`` (causal only) applies the Mistral sliding-window band
    PER SEGMENT — band-exterior tiles are dead tiles (no compute, no KV
    DMA), like cross-segment tiles.
    """
    if window_size is not None and not causal:
        raise ValueError("window_size requires causal=True")
    if window_size is not None and window_size < 1:
        raise ValueError(f"window_size must be >= 1, got {window_size}")
    tq, h, d = q.shape
    tk, hk = k.shape[0], k.shape[1]
    if h % hk != 0:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({hk})")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if block_q is None or block_k is None:
        dbq, dbk = _default_blocks(d)
        block_q = block_q or dbq
        block_k = block_k or dbk
    # lane-aligned blocks; cap at the (padded) token counts
    bq = min(block_q, _round_up(tq, LANES))
    bk = min(block_k, _round_up(tk, LANES))
    pad_q = (-tq) % bq
    pad_k = (-tk) % bk

    cu_q = cu_seqlens_q.astype(jnp.int32)
    cu_k = cu_seqlens_k.astype(jnp.int32)
    seg_q, rel_q = _aux_arrays(cu_q, tq + pad_q, _Q_PAD_SEG, _REL_LO,
                               cu_other=cu_k)
    seg_k, rel_k = _aux_arrays(cu_k, tk + pad_k, _K_PAD_SEG, _REL_HI)
    win = None if window_size is None else int(window_size)
    run_map, full_map = _tile_maps(seg_q, rel_q, seg_k, rel_k, bq, bk,
                                   causal, win)

    qs = jax.lax.broadcast_in_dim(seg_q, (tq + pad_q, LANES), (0,))
    qr = jax.lax.broadcast_in_dim(rel_q, (tq + pad_q, LANES), (0,))
    ks = jax.lax.broadcast_in_dim(seg_k, (SUBLANES, tk + pad_k), (1,))
    kr = jax.lax.broadcast_in_dim(rel_k, (SUBLANES, tk + pad_k), (1,))

    qt = jnp.moveaxis(q, 1, 0)  # (H, Tq, D)
    kt = jnp.moveaxis(k, 1, 0)
    vt = jnp.moveaxis(v, 1, 0)
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, pad_k), (0, 0)))

    out = _varlen_htd(qt, kt, vt, qs, qr, ks, kr, run_map, full_map,
                      causal, sm_scale, bq, bk, win)
    if pad_q:
        out = out[:, :tq]
    return jnp.moveaxis(out, 0, 1)
