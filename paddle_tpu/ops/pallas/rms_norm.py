"""RMSNorm — Pallas TPU kernel (fwd + bwd), the analog of the reference's
fused CUDA kernel (paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu —
unverified, SURVEY.md §0/§2.5).

Rows are all leading dims flattened; the feature dim is normalized.
Math (all in f32):
    m  = mean(x^2)          r = rsqrt(m + eps)
    y  = x * r * w
    g  = dy * w
    dx = g * r - x * r^3 * mean(g * x)
    dw = sum_rows(dy * x * r)
The dw reduction accumulates across row blocks in a VMEM scratch; the TPU
grid is sequential so this is race-free (and interpret mode preserves it).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode as _interpret_mode, round_up as _round_up

DEFAULT_BLOCK_ROWS = 256




def _fwd_kernel(x_ref, w_ref, y_ref, r_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)          # (BR, N)
    w = w_ref[...].astype(jnp.float32)          # (1, N)
    m = jnp.mean(x * x, axis=1, keepdims=True)  # (BR, 1)
    r = jax.lax.rsqrt(m + eps)
    y_ref[...] = (x * r * w).astype(y_ref.dtype)
    r_ref[...] = r


def _bwd_kernel(x_ref, w_ref, r_ref, dy_ref, dx_ref, dw_ref, dw_scr,
                *, row_steps):
    ri = pl.program_id(0)

    @pl.when(ri == 0)
    def _init():
        dw_scr[...] = jnp.zeros_like(dw_scr)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    r = r_ref[...]                               # (BR, 1)
    dy = dy_ref[...].astype(jnp.float32)
    g = dy * w
    mean_gx = jnp.mean(g * x, axis=1, keepdims=True)
    dx = g * r - x * (r * r * r) * mean_gx
    dx_ref[...] = dx.astype(dx_ref.dtype)
    dw_scr[...] += jnp.sum(dy * x * r, axis=0, keepdims=True)

    @pl.when(ri == row_steps - 1)
    def _store():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)


def _rms_fwd(x2d, w, eps, block_rows):
    rows, n = x2d.shape
    block_rows = min(block_rows, rows)
    row_steps = pl.cdiv(rows, block_rows)
    y, r = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(row_steps,),
        in_specs=[
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, n), x2d.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(x2d, w.reshape(1, n))
    return y, r


def _rms_bwd(x2d, w, r, dy2d, block_rows):
    rows, n = x2d.shape
    block_rows = min(block_rows, rows)
    row_steps = pl.cdiv(rows, block_rows)
    dx, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, row_steps=row_steps),
        grid=(row_steps,),
        in_specs=[
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, n), x2d.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, n), jnp.float32)],
        interpret=_interpret_mode(),
    )(x2d, w.reshape(1, n), r, dy2d)
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_norm_2d(x2d, w, eps, block_rows):
    y, _ = _rms_fwd(x2d, w, eps, block_rows)
    return y


def _fwd_rule(x2d, w, eps, block_rows):
    y, r = _rms_fwd(x2d, w, eps, block_rows)
    return y, (x2d, w, r)


def _bwd_rule(eps, block_rows, residuals, dy):
    x2d, w, r = residuals
    dx, dw = _rms_bwd(x2d, w, r, dy, block_rows)
    return dx, dw.reshape(w.shape).astype(w.dtype)


_rms_norm_2d.defvjp(_fwd_rule, _bwd_rule)


def rms_norm(x, weight, epsilon=1e-6, block_rows=None):
    """RMSNorm over the last axis; x (..., N), weight (N,)."""
    n = x.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    if block_rows is None:
        # the bwd kernel's scoped-VMEM demand (double-buffered bf16
        # in/out tiles + f32 compute temporaries) scales ~linearly with
        # block*N and measures ~11MB at 256x2048 on v5e (22MB at
        # 256x4096 = compile OOM against the 16MB limit); cap the
        # product at the known-safe 256x2048
        budget = (256 * 2048) // max(n, 1)
        block_rows = max(8, min(DEFAULT_BLOCK_ROWS, _round_up(budget, 8) or 8))
    # pad rows to a full block multiple so no partial/garbage block ever
    # feeds the dw accumulation (padded rows are zeros → zero dy → no-op)
    block = min(block_rows, ((rows + 7) // 8) * 8)
    pad = (-rows) % block
    x2d = x.reshape(rows, n)
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    out = _rms_norm_2d(x2d, weight, epsilon, block)
    if pad:
        out = out[:rows]
    return out.reshape(*lead, n)
