"""Paged (blocked) KV-cache decode attention — Pallas TPU kernel.

The reference's 2.6-era serving attention ``block_multihead_attention``
(paddle/incubate/nn/functional/block_multihead_attention.py + CUDA
kernels under paddle/fluid/operators/fused/ — unverified, SURVEY.md
§0/§2.5) keeps the KV cache as a POOL of fixed-size blocks shared by all
sequences, with a per-sequence block table — memory scales with live
tokens, not batch × max_seq.

TPU-native mechanics: the pool rides in HBM as (HK, num_blocks,
block_size, D); the per-sequence block tables and lengths ride in
scalar-prefetch SMEM, and the BlockSpec index map dereferences the table
directly — each grid step DMAs exactly one pool block, so the gather is
zero-copy (no jnp.take materialization of the cache). Query heads
sharing a KV head (the GQA group) form the rows of the score matmul, as
in the contiguous-cache decode kernel. Blocks past a sequence's length
re-point at pool block 0 (the DMA is elided) and are predicated off.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode as _interpret_mode

NEG_INF = -1e30


def _paged_kernel(tables_ref, lens_ref, kscale_ref, vscale_ref, q_ref,
                  k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, sm_scale,
                  block_size, steps, group, has_scales):
    b = pl.program_id(0)
    h = pl.program_id(1)
    ki = pl.program_id(2)
    length = lens_ref[b]

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(ki * block_size < length)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)   # (G, D)
        k = k_ref[0, 0].astype(jnp.float32)   # (BS, D)
        v = v_ref[0, 0].astype(jnp.float32)
        if has_scales:
            # int8 KV pools dequantize HERE, in VMEM — the cache stays
            # int8 in HBM (half the residency of a bf16 pool); static
            # flag so float pools keep the multiply-free hot loop
            k = k * kscale_ref[h]
            v = v * vscale_ref[h]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                           # (G, BS)
        pos = ki * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (group, block_size), 1
        )
        mask = pos < length
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    @pl.when(ki == steps - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, block_tables, seq_lens,
                           sm_scale=None, k_scale=None, v_scale=None):
    """One-step decode attention over a paged KV pool.

    Args:
        q: (B, H, D) or (B, 1, H, D) — the new token's query heads.
        k_pool, v_pool: (num_blocks, block_size, HK, D) — the shared
            block pool (paddle's cache layout, block-major). May be int8
            when per-head dequant scales are supplied.
        block_tables: (B, max_blocks) int32 — pool block ids per
            sequence, in order; entries past the sequence's length are
            ignored (any value).
        seq_lens: (B,) int32 — valid tokens per sequence (including the
            one being decoded).
        k_scale, v_scale: optional (HK,) f32 per-kv-head DEQUANT scales
            for int8 pools — applied inside the kernel so the int8 bytes
            are what rides HBM.
    Returns (B, H, D) (or (B, 1, H, D) matching q's rank), in the
    QUERY's dtype.
    """
    squeeze = False
    if q.ndim == 4:
        q = q[:, 0]
        squeeze = True
    b, h, d = q.shape
    num_blocks, block_size, hk = k_pool.shape[:3]
    if h % hk != 0:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({hk})")
    group = h // hk
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    steps = block_tables.shape[1]

    qg = q.reshape(b, hk, group, d)
    # (HK, NB, BS, D): head-major so one grid step pulls one (BS, D) tile
    kp = jnp.moveaxis(k_pool, 2, 0)
    vp = jnp.moveaxis(v_pool, 2, 0)

    lens = seq_lens.astype(jnp.int32)
    tables = block_tables.astype(jnp.int32)
    ks = (jnp.ones((hk,), jnp.float32) if k_scale is None
          else jnp.asarray(k_scale, jnp.float32).reshape(hk))
    vs = (jnp.ones((hk,), jnp.float32) if v_scale is None
          else jnp.asarray(v_scale, jnp.float32).reshape(hk))

    def pool_idx(b_, h_, ki, tables_ref, lens_ref, ks_ref, vs_ref):
        # dead step (past this sequence's blocks) → re-point at block 0;
        # the repeated DMA is elided and the body is predicated off
        live = ki * block_size < lens_ref[b_]
        blk = jax.lax.select(live, tables_ref[b_, ki], 0)
        return (h_, blk, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, hk, steps),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda b_, h_, ki, t, ln, ks_, vs_: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, block_size, d), pool_idx),
            pl.BlockSpec((1, 1, block_size, d), pool_idx),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, d),
            lambda b_, h_, ki, t, ln, ks_, vs_: (b_, h_, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel, sm_scale=sm_scale, block_size=block_size,
            steps=steps, group=group,
            has_scales=k_scale is not None or v_scale is not None,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, group, d), q.dtype),
        interpret=_interpret_mode(),
    )(tables, lens, ks, vs, qg, kp, vp)
    out = out.reshape(b, h, d)
    return out[:, None] if squeeze else out


def paged_cache_write(k_pool, v_pool, k_new, v_new, block_tables, positions):
    """Write one new token's K/V per sequence into the pool.

    k_new/v_new: (B, HK, D); positions: (B,) int32 absolute token index
    (the block table must already map position // block_size).
    Returns the updated pools (functionally).
    """
    block_size = k_pool.shape[1]
    blk = jnp.take_along_axis(
        block_tables.astype(jnp.int32),
        (positions[:, None] // block_size).astype(jnp.int32), axis=1,
    )[:, 0]
    off = positions.astype(jnp.int32) % block_size
    k_pool = k_pool.at[blk, off].set(k_new)
    v_pool = v_pool.at[blk, off].set(v_new)
    return k_pool, v_pool
