"""Flash attention — the Pallas TPU kernel replacing the reference's
vendored flash-attn CUDA library (reference:
paddle/phi/kernels/gpu/flash_attn_kernel.cu + third_party/flashattn —
unverified, SURVEY.md §0/§2.5).

Blockwise online-softmax forward + recompute backward (dq and dk/dv
kernels), wrapped in jax.custom_vjp. Public layout is paddle's
(batch, seq, heads, head_dim); kernels run (batch, heads, seq, head_dim).

Notes on TPU legality (Mosaic lowering):
- LSE is carried as (B, H, S, 1): a (1, 1, block_q, 1) block has its last
  dim equal to the array dim (1) and second-to-last divisible by 8, which
  lowers; a (1, 1, block_q) block does not (second-to-last dim 1).
- Causal masking is bottom-right aligned (`q_pos + (sk - sq) >= k_pos`),
  matching paddle / the XLA fallback's `tril(k=sk-sq)` when seq_q != seq_k.
- Ragged sequence lengths are handled by padding to block multiples and
  masking `k_pos >= sk` inside the kernel; padded query rows are sliced
  off on exit.
- GQA/MQA: forward and dq index the shared KV head via the BlockSpec index
  map (no materialisation); only the dk/dv kernel sees KV repeated per
  query head, with the per-group sum applied after.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._utils import interpret_mode as _interpret_mode, round_up as _round_up

NEG_INF = -1e30


def _default_blocks(head_dim):
    """Measured on v5e: large blocks amortize the per-grid-step overhead —
    (1024, 1024) is ~9x faster than (128, 128) for d=64 fwd+bwd, and the
    round-3 min-of-3 sweep confirmed it also wins at d=128 (1.41 ms vs
    1.69 ms at (512, 512) for S=2048 fwd+bwd). Above d=128 drop to
    (256, 256) to stay within VMEM."""
    if head_dim <= 128:
        return 1024, 1024
    return 256, 256




def _run_full(qi, ki, block_q, block_k, causal, causal_offset, kv_len,
              window=None):
    """(run, full) tile validity: ``run`` = the tile contributes at all
    (not past the kv length / not entirely outside the causal band);
    ``full`` = every (q, k) pair in the tile is valid, i.e. exactly the
    condition under which _mask_for_block is all-true — interior tiles
    skip the mask build. Shared by fwd/dq/dkv so the boundary math can
    never desynchronize between forward and backward. ``window`` (with
    causal) restricts each query to the last ``window`` keys — tiles
    entirely BELOW the band are skipped too, making long-sequence
    sliding-window cost O(S * window)."""
    run = ki * block_k < kv_len
    full = (ki + 1) * block_k <= kv_len
    if causal:
        run = run & (ki * block_k <= (qi + 1) * block_q - 1 + causal_offset)
        full = full & (
            (ki + 1) * block_k - 1 <= qi * block_q + causal_offset)
        if window is not None:
            # band lower edge: k_pos >= q_pos + causal_offset - window + 1
            run = run & ((ki + 1) * block_k - 1
                         >= qi * block_q + causal_offset - window + 1)
            full = full & (
                ki * block_k
                >= (qi + 1) * block_q - 1 + causal_offset - window + 1)
    return run, full


def _kv_band_clamp(block_q, block_k, causal, causal_offset, window,
                   kv_steps):
    """Index-map clamp: re-point a dead kv tile at the nearest LIVE tile
    for its q row — consecutive repeated indices elide the DMA (the
    paged kernel's dead-step trick), so causal upper-triangle tiles and
    window below-band tiles cost no HBM traffic, not just no compute."""
    import jax.numpy as jnp

    def clamp(qi, ki):
        if not causal:
            return ki
        hi = jnp.minimum(kv_steps - 1,
                         ((qi + 1) * block_q - 1 + causal_offset)
                         // block_k)
        lo = 0
        if window is not None:
            lo = jnp.maximum(
                0, (qi * block_q + causal_offset - window + 1) // block_k)
        return jnp.clip(ki, lo, hi)

    return clamp


def _q_band_clamp(block_q, block_k, causal, causal_offset, window, q_steps):
    """Transpose of _kv_band_clamp for the dkv kernel's q-side fetches."""
    import jax.numpy as jnp

    def clamp(ki, qi):
        if not causal:
            return qi
        lo = jnp.maximum(0, (ki * block_k - causal_offset) // block_q)
        hi = q_steps - 1
        if window is not None:
            hi = jnp.minimum(
                q_steps - 1,
                ((ki + 1) * block_k - 1 + window - 1 - causal_offset)
                // block_q)
        return jnp.clip(qi, lo, hi)

    return clamp


def _mask_for_block(qi, ki, block_q, block_k, causal, causal_offset, kv_len,
                    window=None):
    """Boolean validity mask (BQ, BK) for one (q-block, kv-block) tile."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = k_pos < kv_len
    if causal:
        mask = mask & (q_pos + causal_offset >= k_pos)
        if window is not None:
            mask = mask & (k_pos >= q_pos + causal_offset - window + 1)
    return mask


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, causal, causal_offset, kv_len,
                sm_scale, block_q, block_k, kv_steps, window=None):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # interior (fully-valid) tiles skip the mask build entirely — the
    # iota/compare/where work on a (BQ, BK) tile is pure VPU cost and
    # dominates diagonal-heavy causal grids (round-5 fix, mirroring the
    # varlen kernel's run/full split)
    run, full = _run_full(qi, ki, block_q, block_k, causal, causal_offset,
                          kv_len, window)

    def _accumulate(masked):
        # matmul INPUTS stay in the storage dtype (bf16 on TPU) with f32
        # ACCUMULATION via preferred_element_type — an .astype(f32) on
        # q/k/v before the dot forces quarter-rate f32 MXU passes
        # (round-5 fix: this was the "attention at ~50% of the matmul
        # tier" cost in the round-4 long-context rows)
        q = q_ref[0, 0]  # (BQ, D)
        k = k_ref[0, 0]  # (BK, D)
        v = v_ref[0, 0]  # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (BQ, BK) f32
        if masked:
            mask = _mask_for_block(qi, ki, block_q, block_k, causal,
                                   causal_offset, kv_len, window)
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]  # (BQ, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        if masked:
            # fully-masked rows keep m=NEG_INF; mask p explicitly so
            # exp(NEG_INF - NEG_INF) = 1 cannot leak in
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(run & full)
    def _interior():
        _accumulate(False)

    @pl.when(run & ~full)
    def _boundary():
        _accumulate(True)

    @pl.when(ki == kv_steps - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:] + jnp.log(l)


def _flash_fwd(q, k, v, causal, causal_offset, kv_len, sm_scale,
               block_q, block_k, window=None):
    """q: (B,H,Sq,D) block-multiple padded; k/v: (B,HK,Sk,D)."""
    b, h, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    group = h // hk
    q_steps = pl.cdiv(sq, block_q)
    kv_steps = pl.cdiv(sk, block_k)

    kernel = functools.partial(
        _fwd_kernel, causal=causal, causal_offset=causal_offset,
        kv_len=kv_len, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, kv_steps=kv_steps,
        window=window,
    )
    kvc = _kv_band_clamp(block_q, block_k, causal, causal_offset, window,
                         kv_steps)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, q_steps, kv_steps),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki: (b_, h_ // group,
                                                 kvc(qi, ki), 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki: (b_, h_ // group,
                                                 kvc(qi, ki), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(q, k, v)
    return out, lse


# --------------------------------------------------------------------------
# backward: dq kernel (grid over q blocks, scan kv blocks)
# --------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, causal, causal_offset, kv_len, sm_scale,
                   block_q, block_k, kv_steps, window=None):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run, full = _run_full(qi, ki, block_q, block_k, causal, causal_offset,
                          kv_len, window)

    def _body(masked):
        # storage-dtype matmul inputs + f32 accumulation (see _fwd_kernel)
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]    # (BQ, 1)
        delta = delta_ref[0, 0]  # (BQ, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        p = jnp.exp(s - lse)
        if masked:
            mask = _mask_for_block(qi, ki, block_q, block_k, causal,
                                   causal_offset, kv_len, window)
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )

    @pl.when(run & full)
    def _interior():
        _body(False)

    @pl.when(run & ~full)
    def _boundary():
        _body(True)

    @pl.when(ki == kv_steps - 1)
    def _store():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


# --------------------------------------------------------------------------
# backward: dk/dv kernel (grid over kv blocks, scan q blocks)
# --------------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, causal, causal_offset,
                    kv_len, sm_scale, block_q, block_k, q_steps, window=None):
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run, full = _run_full(qi, ki, block_q, block_k, causal, causal_offset,
                          kv_len, window)

    def _body(masked):
        # storage-dtype matmul inputs + f32 accumulation (see _fwd_kernel)
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        p = jnp.exp(s - lse)  # (BQ, BK) f32
        if masked:
            mask = _mask_for_block(qi, ki, block_q, block_k, causal,
                                   causal_offset, kv_len, window)
            p = jnp.where(mask, p, 0.0)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )

    @pl.when(run & full)
    def _interior():
        _body(False)

    @pl.when(run & ~full)
    def _boundary():
        _body(True)

    @pl.when(qi == q_steps - 1)
    def _store():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(causal, causal_offset, kv_len, sm_scale, block_q, block_k,
               window, residuals, g):
    q, k, v, out, lse = residuals
    do = g[0] if isinstance(g, tuple) else g
    b, h, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    group = h // hk
    q_steps = pl.cdiv(sq, block_q)
    kv_steps = pl.cdiv(sk, block_k)

    # GQA: dq reads the shared KV head zero-copy via its index map (like
    # the forward); only the dk/dv kernel needs KV materialised per query
    # head, with the per-group reduction applied after.
    if group > 1:
        k_r = jnp.repeat(k, group, axis=1)
        v_r = jnp.repeat(v, group, axis=1)
    else:
        k_r, v_r = k, v

    # delta = rowsum(do * out) — tiny, do it in XLA; carried as (B,H,Sq,1)
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )

    common = dict(causal=causal, causal_offset=causal_offset, kv_len=kv_len,
                  sm_scale=sm_scale, block_q=block_q, block_k=block_k,
                  window=window)

    kvc = _kv_band_clamp(block_q, block_k, causal, causal_offset, window,
                         kv_steps)
    qc = _q_band_clamp(block_q, block_k, causal, causal_offset, window,
                       q_steps)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, kv_steps=kv_steps, **common),
        grid=(b, h, q_steps, kv_steps),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki: (b_, h_ // group,
                                                 kvc(qi, ki), 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki: (b_, h_ // group,
                                                 kvc(qi, ki), 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret_mode(),
    )(q, k, v, do, lse, delta)

    dk_r, dv_r = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, q_steps=q_steps, **common),
        grid=(b, h, kv_steps, q_steps),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, ki, qi: (b_, h_, qc(ki, qi), 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, ki, qi: (b_, h_, qc(ki, qi), 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, ki, qi: (b_, h_, qc(ki, qi), 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, ki, qi: (b_, h_, qc(ki, qi), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(q, k_r, v_r, do, lse, delta)

    if group > 1:
        dk = dk_r.reshape(b, hk, group, sk, d).sum(axis=2).astype(k.dtype)
        dv = dv_r.reshape(b, hk, group, sk, d).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_r, dv_r
    return dq, dk, dv


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_attention_bhsd(q, k, v, causal, causal_offset, kv_len, sm_scale,
                          block_q, block_k, window=None):
    out, _ = _flash_fwd(q, k, v, causal, causal_offset, kv_len, sm_scale,
                        block_q, block_k, window)
    return out


def _fwd_rule(q, k, v, causal, causal_offset, kv_len, sm_scale,
              block_q, block_k, window=None):
    out, lse = _flash_fwd(q, k, v, causal, causal_offset, kv_len, sm_scale,
                          block_q, block_k, window)
    return out, (q, k, v, out, lse)


def _bwd_rule(causal, causal_offset, kv_len, sm_scale, block_q, block_k,
              window, residuals, g):
    return _flash_bwd(causal, causal_offset, kv_len, sm_scale,
                      block_q, block_k, window, residuals, g)


_flash_attention_bhsd.defvjp(_fwd_rule, _bwd_rule)


def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=None, block_k=None, window_size=None):
    """Flash attention over paddle layout (B, S, H, D).

    Supports GQA/MQA (H a multiple of HK), cross-attention lengths
    (bottom-right causal alignment), arbitrary sequence lengths
    (internally padded to block multiples), and causal SLIDING-WINDOW
    attention (``window_size`` = the number of most-recent keys each
    query may attend to, itself included — Mistral semantics; tiles
    entirely outside the band are skipped, so cost is O(S * window)).
    """
    if window_size is not None:
        if not causal:
            raise ValueError(
                "window_size requires causal=True (a non-causal window "
                "is ambiguous about its anchor)")
        if window_size < 1:
            raise ValueError(f"window_size must be >= 1, got {window_size}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if block_q is None or block_k is None:
        dbq, dbk = _default_blocks(q.shape[-1])
        block_q = block_q or dbq
        block_k = block_k or dbk
    h, hk = q.shape[2], k.shape[2]
    if h % hk != 0:
        raise ValueError(f"query heads ({h}) must be a multiple of kv heads ({hk})")
    qt = jnp.swapaxes(q, 1, 2)  # (B, H, Sq, D)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    sq, sk = qt.shape[2], kt.shape[2]
    bq = min(block_q, _round_up(sq, 8))
    bk = min(block_k, _round_up(sk, 8))
    pad_q = (-sq) % bq
    pad_k = (-sk) % bk
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    causal_offset = sk - sq  # bottom-right alignment, real lengths
    win = None if window_size is None else int(window_size)
    out = _flash_attention_bhsd(qt, kt, vt, causal, causal_offset, sk,
                                sm_scale, bq, bk, win)
    if pad_q:
        out = out[:, :, :sq]
    return jnp.swapaxes(out, 1, 2)
