"""Flash attention — the Pallas TPU kernel replacing the reference's
vendored flash-attn CUDA library (reference:
paddle/phi/kernels/gpu/flash_attn_kernel.cu + third_party/flashattn —
unverified, SURVEY.md §0/§2.5).

Blockwise online-softmax forward + recompute backward (dq and dk/dv
kernels), wrapped in jax.custom_vjp. Public layout is paddle's
(batch, seq, heads, head_dim); kernels run (batch, heads, seq, head_dim).
Supports causal masking; sm_scale defaults to 1/sqrt(D).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _interpret_mode():
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, causal, sm_scale, block_q, block_k,
                kv_steps):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = True
    if causal:
        # kv block strictly after the last q row of this block → skip
        run = ki * block_k <= (qi + 1) * block_q - 1

    @pl.when(run if causal else True)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)  # (BQ, D)
        k = k_ref[0, 0].astype(jnp.float32)  # (BK, D)
        v = v_ref[0, 0].astype(jnp.float32)  # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (BQ, BK)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[:]  # (BQ, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(ki == kv_steps - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:] + jnp.log(l))[:, 0]


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    q_steps = pl.cdiv(sq, block_q)
    kv_steps = pl.cdiv(sk, block_k)

    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, kv_steps=kv_steps,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, q_steps, kv_steps),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b_, h_, qi, ki: (b_, h_, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(q, k, v)
    return out, lse


# --------------------------------------------------------------------------
# backward: dq kernel (grid over q blocks, scan kv blocks)
# --------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, causal, sm_scale, block_q, block_k, kv_steps):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = ki * block_k <= (qi + 1) * block_q - 1

    @pl.when(run if causal else True)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]  # (BQ,1)
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # softmax probabilities
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == kv_steps - 1)
    def _store():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


# --------------------------------------------------------------------------
# backward: dk/dv kernel (grid over kv blocks, scan q blocks)
# --------------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, causal, sm_scale,
                    block_q, block_k, q_steps):
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        # q block entirely before this kv block → no contribution
        run = (qi + 1) * block_q - 1 >= ki * block_k

    @pl.when(run if causal else True)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # (BQ, BK)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == q_steps - 1)
    def _store():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(causal, sm_scale, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    do = g[0] if isinstance(g, tuple) else g
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    q_steps = pl.cdiv(sq, block_q)
    kv_steps = pl.cdiv(sk, block_k)

    # delta = rowsum(do * out) — tiny, do it in XLA
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (B,H,Sq)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, kv_steps=kv_steps,
        ),
        grid=(b, h, q_steps, kv_steps),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b_, h_, qi, ki: (b_, h_, qi)),
            pl.BlockSpec((1, 1, block_q), lambda b_, h_, qi, ki: (b_, h_, qi)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret_mode(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, q_steps=q_steps,
        ),
        grid=(b, h, kv_steps, q_steps),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, ki, qi: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, ki, qi: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b_, h_, ki, qi: (b_, h_, qi)),
            pl.BlockSpec((1, 1, block_q), lambda b_, h_, ki, qi: (b_, h_, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_bhsd(q, k, v, causal, sm_scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out


def _fwd_rule(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _bwd_rule(causal, sm_scale, block_q, block_k, residuals, g):
    return _flash_bwd(causal, sm_scale, block_q, block_k, residuals, g)


_flash_attention_bhsd.defvjp(_fwd_rule, _bwd_rule)


def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Flash attention over paddle layout (B, S, H, D)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    # pad seq to block multiples (masked out by causal/softmax renorm)
    sq, sk = qt.shape[2], kt.shape[2]
    bq = min(block_q, max(sq, 8))
    bk = min(block_k, max(sk, 8))
    pad_q = (-sq) % bq
    pad_k = (-sk) % bk
    if pad_q or pad_k:
        # fall back to XLA attention on ragged shapes (simplicity; the
        # training path uses block-multiple seq lens)
        raise ValueError(
            f"flash_attention requires seq multiples of block "
            f"({bq}, {bk}); got q={sq}, k={sk}"
        )
    out = _flash_attention_bhsd(qt, kt, vt, causal, sm_scale, bq, bk)
    return jnp.swapaxes(out, 1, 2)
