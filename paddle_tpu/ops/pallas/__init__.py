from .flash_attention import flash_attention  # noqa: F401
from .rms_norm import rms_norm  # noqa: F401
from .decode_attention import decode_attention  # noqa: F401
from .varlen_flash_attention import varlen_flash_attention  # noqa: F401
from .paged_attention import paged_decode_attention, paged_cache_write  # noqa: F401
