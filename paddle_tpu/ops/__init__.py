"""paddle_tpu.ops — native-kernel tier (Pallas on TPU).

The reference ships CUDA ``fused_*`` kernels (SURVEY.md §2.5); here the
equivalents are Pallas TPU kernels with XLA fallbacks, dispatched through
the same functional surface (F.scaled_dot_product_attention, F.rms_norm,
incubate.fused_multi_transformer).
"""
from . import pallas  # noqa: F401
