"""Fully-jitted training step — the perf path of the framework.

The reference reaches peak throughput through static-graph execution with
fused ops (SURVEY.md §3.2/§3.3); the TPU-native equivalent is ONE
``jax.jit``-compiled function per training step: forward (via
``functional_call`` on the live Layer), loss, backward (``jax.grad``),
and the optimizer's functional multi-tensor update — all fused by XLA,
with parameter/state buffers donated so updates are in-place in HBM.

Under a ``jax.sharding.Mesh`` the params/opt-states are already placed
with NamedShardings (fleet TP layers / ZeRO state sharding); jit infers
in-shardings from placement and GSPMD inserts the ICI collectives.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import autograd
from . import functional_call
from ..parallel import mesh as mesh_state

__all__ = ["JittedTrainStep"]


class JittedTrainStep:
    """Compile the whole (forward, loss, backward, update) into one XLA
    program.

    Args:
        model: nn.Layer (params may carry NamedShardings from TP layers).
        criterion: callable(model_output, *labels) -> scalar loss Tensor.
        optimizer: paddle_tpu Optimizer (its functional bridge is used;
            the live optimizer object's state is NOT consumed).
        state_sharding_axis: optional mesh axis name — optimizer states
            are sharded over it along dim 0 when divisible (ZeRO-1/2: the
            reference's GroupShardedOptimizerStage2 semantics).
        input_batch_axes: mesh axes for the leading (batch) dim of every
            input (default ``("dp",)`` when a mesh is installed).
        donate: donate param/state buffers (in-place HBM update).
    """

    def __init__(self, model, criterion, optimizer,
                 state_sharding_axis=None, input_batch_axes=None,
                 donate=True):
        self._model = model
        self._criterion = criterion
        self._optimizer = optimizer
        self._params = [p for _, p in model.named_parameters()]
        self._buffers = [b for _, b in model.named_buffers()]
        self._p_vals = [p._value for p in self._params]
        self._b_vals = [b._value for b in self._buffers]
        if mesh_state.has_mesh():
            # commit EVERY param/buffer to the mesh (replicated when not
            # already placed): an uncommitted array leaves
            # allow_spmd_sharding_propagation_to_parameters open, and the
            # partitioner then back-propagates optimizer-state shardings
            # into e.g. layernorm weights, poisoning the whole forward
            # with involuntary-remat reshards
            self._p_vals = [_commit_to_mesh(v) for v in self._p_vals]
            self._b_vals = [_commit_to_mesh(v) for v in self._b_vals]
            for p, v in zip(self._params, self._p_vals):
                p._value = v
            for b, v in zip(self._buffers, self._b_vals):
                b._value = v
        self._s_vals = optimizer.functional_state_init(self._p_vals)
        self._decay_flags = [optimizer._decay_enabled(p) for p in self._params]
        self._step_no = 0
        self._input_batch_axes = input_batch_axes
        if state_sharding_axis and mesh_state.has_mesh():
            self._s_vals = _shard_states(
                self._s_vals, state_sharding_axis, self._p_vals)

        model_ref = model
        criterion_ref = criterion
        opt_ref = optimizer
        decay_flags = self._decay_flags
        # Pin grads of TENSOR-PARALLEL params to the param's own layout:
        # without it, 'sharding'-sharded moments leak their axis backward
        # through the bwd matmuls and GSPMD full-remats params whose
        # device order differs. Replicated params stay unpinned so their
        # partial-sum grads can reduce-scatter straight into ZeRO-sharded
        # moments (pinning those would force an early all-reduce).
        def _pin_sharding(v):
            sh = _named_sharding_of(v)
            if sh is not None and any(s is not None for s in sh.spec):
                return sh
            return None

        grad_pins = (
            [_pin_sharding(v) for v in self._p_vals]
            if mesh_state.has_mesh() else [None] * len(self._p_vals)
        )

        def one_step(p_vals, s_vals, b_vals, rng, lr, step_no, inputs, labels):
            from ..core.random import traced_key_scope

            def loss_of(pv):
                in_t = [Tensor(x, stop_gradient=True) for x in inputs]
                lb_t = [Tensor(x, stop_gradient=True) for x in labels]
                with autograd.no_grad(), traced_key_scope(rng):
                    def fwd_and_loss(*args):
                        n_in = len(in_t)
                        out = model_ref(*args[:n_in])
                        return criterion_ref(out, *args[n_in:])

                    loss_t, new_b = functional_call(
                        model_ref, fwd_and_loss, in_t + lb_t, {}, pv, b_vals
                    )
                return loss_t._value, new_b

            (loss, new_b), grads = jax.value_and_grad(
                loss_of, has_aux=True)(p_vals)
            grads = [
                jax.lax.with_sharding_constraint(g, sh)
                if g is not None and sh is not None else g
                for g, sh in zip(grads, grad_pins)
            ]
            new_p, new_s = opt_ref.functional_apply(
                p_vals, grads, s_vals, lr, step_no, decay_flags)
            return loss, new_p, new_s, new_b

        def step_fn(p_vals, s_vals, b_vals, rng, lr, step_no, inputs, labels):
            return one_step(p_vals, s_vals, b_vals, rng, lr, step_no,
                            inputs, labels)

        def multi_step_fn(p_vals, s_vals, b_vals, rng, lr, step0,
                          inputs_stacked, labels_stacked):
            # K train steps in ONE XLA program (lax.scan over the batch
            # stack): amortizes host dispatch — the TPU-native analog of
            # the reference Executor running a multi-iteration program
            def body(carry, xs):
                p, s, b, step_no = carry
                in_i, lb_i = xs
                rng_i = jax.random.fold_in(rng, step_no)
                loss, p, s, b = one_step(p, s, b, rng_i, lr, step_no,
                                         in_i, lb_i)
                return (p, s, b, step_no + 1), loss

            (p, s, b, _), losses = jax.lax.scan(
                body, (p_vals, s_vals, b_vals, step0),
                (inputs_stacked, labels_stacked))
            return losses, p, s, b

        self._donate = bool(donate)
        self._step_fn = step_fn  # analysis hook: the pure step function
        donate_args = (0, 1, 2) if donate else ()
        jit_kw = {}
        if mesh_state.has_mesh():
            # pin state outputs to their input placements: donation stays
            # buffer-exact and the partitioner never "improves" the
            # round-trip sharding (a source of involuntary remat reshards);
            # only mesh placements are pinnable — uncommitted arrays
            # (SingleDeviceSharding) stay unconstrained
            p_sh = [_named_sharding_of(v) for v in self._p_vals]
            s_sh = jax.tree_util.tree_map(_named_sharding_of, self._s_vals)
            b_sh = [_named_sharding_of(v) for v in self._b_vals]
            jit_kw = {"out_shardings": (None, p_sh, s_sh, b_sh)}
        self._jitted = jax.jit(step_fn, donate_argnums=donate_args, **jit_kw)
        self._jitted_multi = jax.jit(
            multi_step_fn, donate_argnums=donate_args, **jit_kw)

    def _batch_args(self, inputs, labels):
        """Normalize/place one example batch: (in_vals, lb_vals, lr,
        step_no) exactly as __call__ would feed the jitted program."""
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        in_vals = [self._place_input(t) for t in inputs]
        lb_vals = [self._place_input(t) for t in labels]
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        step_no = jnp.asarray(self._step_no + 1, jnp.int32)
        return in_vals, lb_vals, lr, step_no

    def __call__(self, inputs, labels):
        """inputs/labels: Tensor or list of Tensors. Returns loss Tensor."""
        in_vals, lb_vals, lr, step_no = self._batch_args(inputs, labels)
        from ..core.random import next_key

        loss, self._p_vals, self._s_vals, self._b_vals = self._jitted(
            self._p_vals, self._s_vals, self._b_vals, next_key(), lr,
            step_no, in_vals, lb_vals,
        )
        self._step_no += 1
        return Tensor(loss)

    # -- lowered-IR hooks (paddle_tpu.analysis audits compile THESE) -------
    def lower(self, inputs, labels):
        """Lower (do not run) the single-step program for the CURRENT
        param/state values and the given example batch; returns the
        ``jax.stages.Lowered`` whose StableHLO / compiled HLO the
        analysis passes walk."""
        in_vals, lb_vals, lr, step_no = self._batch_args(inputs, labels)
        from ..core.random import next_key

        return self._jitted.lower(
            self._p_vals, self._s_vals, self._b_vals, next_key(), lr,
            step_no, in_vals, lb_vals,
        )

    def step_jaxpr(self, inputs, labels):
        """The step's ClosedJaxpr (pre-partitioning IR) for the current
        state — the dtype-promotion auditor walks this."""
        in_vals, lb_vals, lr, step_no = self._batch_args(inputs, labels)
        from ..core.random import next_key

        return jax.make_jaxpr(self._step_fn)(
            self._p_vals, self._s_vals, self._b_vals, next_key(), lr,
            step_no, in_vals, lb_vals,
        )

    def donatable_leaf_count(self):
        """How many leading jit arguments are param/state/buffer leaves
        (the ones ``donate=True`` hands back to XLA): the donation audit
        checks exactly these are aliased in the lowered program."""
        flat, _ = jax.tree_util.tree_flatten(
            (self._p_vals, self._s_vals, self._b_vals))
        return len(flat)

    @property
    def donate(self):
        return self._donate

    def run_steps(self, inputs_stacked, labels_stacked):
        """Run K train steps in ONE dispatch. inputs/labels carry a leading
        step dim (K, batch, ...); returns the (K,) per-step losses."""
        if not isinstance(inputs_stacked, (list, tuple)):
            inputs_stacked = [inputs_stacked]
        if not isinstance(labels_stacked, (list, tuple)):
            labels_stacked = [labels_stacked]
        in_vals = [self._place_input(t, stacked=True) for t in inputs_stacked]
        lb_vals = [self._place_input(t, stacked=True) for t in labels_stacked]
        from ..core.random import next_key

        k = in_vals[0].shape[0]
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        step0 = jnp.asarray(self._step_no + 1, jnp.int32)
        losses, self._p_vals, self._s_vals, self._b_vals = self._jitted_multi(
            self._p_vals, self._s_vals, self._b_vals, next_key(), lr,
            step0, in_vals, lb_vals,
        )
        self._step_no += k
        return Tensor(losses)

    def _place_input(self, t, stacked=False):
        v = t._value if isinstance(t, Tensor) else jnp.asarray(t)
        if mesh_state.has_mesh():
            axes = self._input_batch_axes
            if axes is None:
                axes = ("dp",) if mesh_state.mesh_axis_size("dp") > 1 else ()
            if axes:
                from jax.sharding import NamedSharding, PartitionSpec

                lead = [None] if stacked else []
                spec = PartitionSpec(
                    *lead, axes, *([None] * (v.ndim - len(lead) - 1)))
                v = jax.device_put(
                    v, NamedSharding(mesh_state.get_mesh(), spec))
        return v

    def sync_to_model(self):
        """Write the jitted state back to the live Layer/Optimizer (for
        save/load or switching to eager)."""
        for p, v in zip(self._params, self._p_vals):
            p._value = v
        for b, v in zip(self._buffers, self._b_vals):
            b._value = v
        for p, s in zip(self._params, self._s_vals):
            self._optimizer._states[id(p)] = s
        self._optimizer._step_count = self._step_no

    @property
    def params(self):
        return self._p_vals


def _named_sharding_of(v):
    """The array's NamedSharding, or None when uncommitted/off-mesh."""
    from jax.sharding import NamedSharding

    sh = getattr(v, "sharding", None)
    return sh if isinstance(sh, NamedSharding) else None


def _commit_to_mesh(v):
    """Give an uncommitted array a replicated NamedSharding on the mesh."""
    from jax.sharding import NamedSharding, PartitionSpec

    if not isinstance(v, jax.Array):
        return v
    if _named_sharding_of(v) is not None:
        return v
    mesh = mesh_state.get_mesh()
    spec = PartitionSpec(*([None] * v.ndim))
    return jax.device_put(v, NamedSharding(mesh, spec))


def _shard_states(states, axis, p_vals):
    """Place optimizer state arrays sharded over ``axis`` (dim 0 when
    divisible) — ZeRO-1/2 optimizer-state partitioning on the mesh.

    Param-shaped states (moments, master weights) MERGE the param's own
    sharding (e.g. TP's mp axis) with the ZeRO axis instead of replacing
    it: a dim-1-mp-sharded param whose moments were dim-0-sharding-only
    would otherwise force the partitioner into replicate-then-repartition
    ("involuntary full rematerialization") at every optimizer update."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = mesh_state.get_mesh()
    size = mesh_state.mesh_axis_size(axis)
    if size <= 1:
        return states

    def _merged_spec(p, v):
        pspec = ()
        psh = _named_sharding_of(p)
        if psh is not None:
            pspec = tuple(psh.spec)
        # ZeRO axis goes MINOR on dim 0 (shared rule, see
        # mesh.merged_dim0_spec): each device's moment shard is a
        # sub-slice of its own param/grad shard.
        return mesh_state.merged_dim0_spec(v.shape, pspec, mesh, axis)

    out = []
    for p, st in zip(p_vals, states):
        def place(v, p=p):
            # 1-D params (norm scales, biases) keep replicated moments:
            # sharding them saves ~hidden_size bytes but their unpinnable
            # grads let the 'sharding' axis propagate backward into the
            # activation grads (involuntary full remats). 2-D+ params
            # carry the actual ZeRO memory win. Replicated still means
            # COMMITTED to the mesh — an uncommitted state input would
            # reopen the propagation hole.
            if not isinstance(v, jax.Array) or v.ndim == 0:
                return v
            if v.ndim < 2:
                return _commit_to_mesh(v)
            if v.shape == p.shape:
                spec = _merged_spec(p, v)
            elif v.shape[0] % size == 0:
                spec = PartitionSpec(axis, *([None] * (v.ndim - 1)))
            else:
                spec = PartitionSpec(*([None] * v.ndim))
            return jax.device_put(v, NamedSharding(mesh, spec))

        out.append(jax.tree_util.tree_map(place, st))
    return out
