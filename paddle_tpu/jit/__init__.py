"""paddle.jit — to_static on jax.jit (reference: python/paddle/jit/ —
unverified, SURVEY.md §0).

The reference lowers Python to ProgramDesc via AST transforms/SOT bytecode
tracing; here XLA is the static runtime, so ``to_static`` wraps the
function in ONE dispatch-op whose kernel is a ``jax.jit``-compiled
functional version of the forward: layer params/buffers are swapped to
traced values inside (functional_call), gradients flow through the outer
``jax.vjp`` exactly like any other op, and buffer mutations (BN running
stats) are returned as auxiliary outputs and written back. Guard-based
retrace = jax.jit's shape/dtype cache plus a static-kwargs key.
"""
from __future__ import annotations

import os

import numpy as np
import jax

from ..core.tensor import Tensor, Parameter
from ..core.dispatch import apply
from ..core import autograd

__all__ = [
    "to_static", "not_to_static", "ignore_module", "save", "load",
    "functional_call", "TranslatedLayer", "enable_to_static",
]

_to_static_enabled = True


def enable_to_static(flag=True):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


# --------------------------------------------------------------------------
# SOT-lite value guards (reference: python/paddle/jit/sot/ guard-based
# cache + graph breaks — unverified, SURVEY.md §0 / hard-part #5).
#
# A bool() on a traced Tensor inside to_static means value-dependent
# Python control flow. Instead of baking one branch silently, to_static:
#   1. breaks the graph (``_GraphBreak``), runs the call EAGERLY, and
#      records every bool() outcome — the guard tuple;
#   2. compiles a specialization per observed guard tuple, which ASSUMES
#      those outcomes at trace time and returns the traced guard
#      predicates as extra outputs;
#   3. on later calls, runs the most-recent specialization and VERIFIES
#      the returned predicate values against the assumptions — a
#      mismatch discards the run and re-specializes via the eager path.
# --------------------------------------------------------------------------
class _GraphBreak(Exception):
    """bool() on a traced Tensor hit an unseen value-dependent branch."""


# distinct value specializations per (signature) cache entry before
# giving up on compilation and running the function eagerly forever
_MAX_GUARD_SPECS = 8


class _GuardContext:
    def __init__(self, mode, assumed=()):
        self.mode = mode  # "trace" | "eager"
        self.assumed = tuple(assumed)
        self.outcomes = []  # eager: concrete bool() results, in order
        self.preds = []     # trace: traced boolean scalars, in order
        self.pred_expect = []  # trace: assumed outcome per traced pred
        # trace: (weakref(owner Tensor), expected bool) for CONCRETE
        # guards — closed-over tensors are trace-time constants, so their
        # predicates cannot be verified in the compiled program; they are
        # re-checked host-side before each cached-spec run instead
        self.host_checks = []
        self._i = 0

    def on_bool(self, value, owner=None):
        if self.mode == "eager":
            out = bool(np.asarray(value))
            self.outcomes.append(out)
            return out
        i = self._i
        self._i += 1
        if i >= len(self.assumed):
            raise _GraphBreak()
        if isinstance(value, jax.core.Tracer):
            # errors at trace time for non-scalar tensors, matching
            # eager bool() semantics
            self.preds.append(jax.numpy.reshape(value != 0, ()))
            self.pred_expect.append(self.assumed[i])
            return self.assumed[i]
        actual = bool(np.asarray(value))
        if actual != self.assumed[i]:
            raise _GraphBreak()  # constant changed between record & trace
        import weakref

        self.host_checks.append(
            (weakref.ref(owner) if owner is not None else None, actual))
        return actual


import threading as _threading

_guard_tls = _threading.local()


def _current_guard_ctx():
    return getattr(_guard_tls, "ctx", None)


class _guard_scope:
    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(_guard_tls, "ctx", None)
        _guard_tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _guard_tls.ctx = self._prev
        return False


def functional_call(layer, fn, args, kwargs, param_values, buffer_values):
    """Run ``fn`` with layer params/buffers temporarily rebound to the given
    (possibly traced) values; returns (output, new_buffer_values)."""
    params = [p for _, p in layer.named_parameters()]
    buffers = [b for _, b in layer.named_buffers()]
    saved_p = [p._value for p in params]
    saved_b = [b._value for b in buffers]
    try:
        for p, v in zip(params, param_values):
            p._value = v
        for b, v in zip(buffers, buffer_values):
            b._value = v
        out = fn(*args, **kwargs)
        new_buf = [b._value for b in buffers]
        return out, new_buf
    finally:
        for p, v in zip(params, saved_p):
            p._value = v
        for b, v in zip(buffers, saved_b):
            b._value = v


class StaticFunction:
    """The object returned by @to_static on a function/Layer.forward."""

    def __init__(self, function, layer=None, input_spec=None,
                 build_strategy=None, full_graph=True):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._jit_cache: dict = {}
        self.__name__ = getattr(function, "__name__", "forward")

    def __get__(self, instance, owner):
        # class-level @to_static decoration: bind like a method
        if instance is None:
            return self
        import types

        return types.MethodType(self, instance)

    def _get_layer(self, args):
        from ..nn.layer.layers import Layer

        if self._layer is not None:
            return self._layer, self._function, args
        fn = self._function
        if hasattr(fn, "__self__") and isinstance(fn.__self__, Layer):
            return fn.__self__, fn, args
        if args and isinstance(args[0], Layer):
            return args[0], fn.__get__(args[0]), args[1:]
        return None, fn, args

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            layer, fn, call_args = self._get_layer(args)
            return fn(*call_args, **kwargs)
        if _current_guard_ctx() is not None:
            # nested to_static under an enclosing trace/eager-record:
            # inline into the enclosing context so its guard machinery
            # sees a single consistent bool() sequence (an inner jit
            # could neither be guard-verified mid-trace nor recorded)
            layer, fn, call_args = self._get_layer(args)
            return fn(*call_args, **kwargs)
        layer, fn, call_args = self._get_layer(args)

        tensor_args = []
        arg_spec = []
        for a in call_args:
            if isinstance(a, np.ndarray):
                a = Tensor(a)  # arrays are data, not static config
            if isinstance(a, Tensor):
                arg_spec.append(("t", len(tensor_args)))
                tensor_args.append(a)
            else:
                arg_spec.append(("s", a))

        params = [p for _, p in layer.named_parameters()] if layer else []
        buffers = [b for _, b in layer.named_buffers()] if layer else []
        n_args = len(tensor_args)
        n_params = len(params)
        training = layer.training if layer is not None else False
        static_key = (
            tuple(
                (kind, repr(v)) if kind == "s" else (kind, v)
                for kind, v in arg_spec
            ),
            tuple(sorted((k, repr(v)) for k, v in kwargs.items())),
            training,
            n_params,
            len(buffers),
        )

        entry = self._jit_cache.get(static_key)
        if entry is None:
            layer_ref = layer
            fn_ref = fn
            spec = list(arg_spec)
            kw = dict(kwargs)

            def build_spec(assumed):
                """Compile a specialization that ASSUMES the recorded
                bool() outcomes (SOT-lite value guards) and returns the
                traced guard predicates for runtime verification."""
                meta = {}

                def jittable(args_vals, param_vals, buffer_vals, rng_key):
                    from ..core.random import traced_key_scope

                    rebuilt = [
                        Tensor(args_vals[v], stop_gradient=True)
                        if kind == "t" else v
                        for kind, v in spec
                    ]
                    ctx = _GuardContext("trace", assumed)
                    with _guard_scope(ctx), autograd.no_grad(), \
                            traced_key_scope(rng_key):
                        if layer_ref is not None:
                            out, new_buf = functional_call(
                                layer_ref, fn_ref, rebuilt, kw, param_vals,
                                buffer_vals,
                            )
                        else:
                            out = fn_ref(*rebuilt, **kw)
                            new_buf = []
                    flat, treedef = jax.tree_util.tree_flatten(
                        out, is_leaf=lambda x: isinstance(x, Tensor)
                    )
                    meta["treedef"] = treedef
                    meta["n_preds"] = len(ctx.preds)
                    meta["pred_expect"] = tuple(ctx.pred_expect)
                    meta["host_checks"] = ctx.host_checks
                    flat_vals = [
                        t._value if isinstance(t, Tensor) else t for t in flat
                    ]
                    return flat_vals, new_buf, ctx.preds

                return jax.jit(jittable), meta

            entry = {"build": build_spec, "specs": {}, "mru": ()}
            self._jit_cache[static_key] = entry

        from ..core.random import next_key

        # eager replays must see the TENSOR-wrapped args (raw ndarray
        # args would dodge Tensor.__bool__, break guard recording, and
        # change the return type)
        eager_args = [
            tensor_args[v] if kind == "t" else v for kind, v in arg_spec
        ]

        if entry.get("eager_only"):
            return fn(*eager_args, **kwargs)

        def run_eager_record():
            """Graph break: run this call eagerly (correct by
            construction), record the bool() outcomes as the guard
            tuple, and make sure a specialization exists for it."""
            ctx = _GuardContext("eager")
            with _guard_scope(ctx):
                out = fn(*eager_args, **kwargs)
            guards = tuple(ctx.outcomes)
            if guards not in entry["specs"]:
                n_value_specs = sum(1 for g in entry["specs"] if g != ())
                if n_value_specs >= _MAX_GUARD_SPECS:
                    # guard-cache thrash (e.g. branching on per-batch
                    # stats): stop compiling, stay eager permanently —
                    # the reference SOT bounds its guard cache the same
                    # way
                    entry["eager_only"] = True
                    return out
                entry["specs"][guards] = entry["build"](guards)
            entry["mru"] = guards
            return out

        guards = entry["mru"] if entry["mru"] in entry["specs"] else ()
        if guards not in entry["specs"]:
            entry["specs"][guards] = entry["build"](guards)
        jitted, meta = entry["specs"][guards]

        # concrete (closed-over) guards are trace-time constants — verify
        # them host-side BEFORE serving the cached spec; a dead weakref
        # or changed value re-routes through the eager path
        for ref_, expect in meta.get("host_checks", []):
            t = ref_() if ref_ is not None else None
            if t is None or bool(np.asarray(t._value)) != expect:
                return run_eager_record()

        rng_key = next_key()
        buffer_vals = [b._value for b in buffers]

        def op_fn(*all_vals):
            a_vals = list(all_vals[:n_args])
            p_vals = list(all_vals[n_args : n_args + n_params])
            b_vals = list(all_vals[n_args + n_params :])
            flat_vals, new_buf, preds = jitted(a_vals, p_vals, b_vals, rng_key)
            return tuple(flat_vals) + tuple(new_buf) + tuple(preds)

        try:
            results = apply(
                op_fn, *tensor_args, *params,
                *[Tensor(v) for v in buffer_vals],
                op_name="to_static",
            )
        except _GraphBreak:
            # value-dependent control flow hit an unseen path at trace
            # time — re-specialize per observed value (SOT guard cache)
            return run_eager_record()
        results = results if isinstance(results, tuple) else (results,)
        n_buf = len(buffers)
        # populated by the trace (which has run by now — apply executed)
        n_preds = meta["n_preds"]
        n_out = len(results) - n_buf - n_preds
        out_flat = list(results[:n_out])
        new_buf = results[n_out : n_out + n_buf]
        pred_ts = results[n_out + n_buf :]
        if n_preds:
            if any(isinstance(t._value, jax.core.Tracer) for t in pred_ts):
                raise TypeError(
                    "a value-guarded to_static function cannot be called "
                    "under an enclosing jax.jit trace: its guards cannot "
                    "be verified mid-trace. Call it outside jit, or use "
                    "paddle.static.nn.cond for the value branch."
                )
            observed = tuple(
                bool(np.asarray(t._value)) for t in pred_ts
            )
            if observed != meta["pred_expect"]:
                # guard check failed: discard this run (buffers not yet
                # written back) and take the eager path, learning the
                # new specialization for next time
                return run_eager_record()
        if guards:
            entry["mru"] = guards
        for b, nb in zip(buffers, new_buf):
            b._value = nb._value
        out = jax.tree_util.tree_unflatten(meta["treedef"], out_flat)
        return out

    # -- introspection (CINN-story surface: lowered StableHLO) --------------
    def concrete_program(self, *args):
        return None

    def lowered(self, *args, **kwargs):
        """Lower the most-recent specialization for these args to a
        ``jax.stages.Lowered`` (compiles the call first if this
        signature was never traced) — the hook ``paddle_tpu.analysis``
        audits to walk a to_static program's StableHLO/compiled HLO."""
        layer, _, call_args = self._get_layer(args)
        tensor_args = [a for a in call_args if isinstance(a, Tensor)]
        params = [p for _, p in layer.named_parameters()] if layer else []
        buffers = [b for _, b in layer.named_buffers()] if layer else []
        if not self._jit_cache:
            self(*args, **kwargs)
        entry = next(iter(self._jit_cache.values()))
        guards = entry["mru"] if entry["mru"] in entry["specs"] else ()
        jitted = entry["specs"][guards][0]
        return jitted.lower(
            [t._value for t in tensor_args],
            [p._value for p in params],
            [b._value for b in buffers],
            jax.random.PRNGKey(0),
        )

    def get_stablehlo(self, *args, **kwargs):
        """Lower the traced function to StableHLO text (the reference's
        CINN fused-subgraph analog — SURVEY.md §2.2 TPU mapping note)."""
        lowered = self.lowered(*args, **kwargs)
        return str(lowered.compiler_ir(dialect="stablehlo"))


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """Decorator/wrapper: paddle.jit.to_static."""
    from ..nn.layer.layers import Layer

    def decorate(obj):
        if isinstance(obj, Layer):
            static_fn = StaticFunction(
                obj.forward, layer=obj, input_spec=input_spec,
                build_strategy=build_strategy,
            )
            obj.forward = static_fn
            return obj
        return StaticFunction(obj, input_spec=input_spec,
                              build_strategy=build_strategy)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


class TranslatedLayer:
    """Inference layer reconstructed from an exported program (jit.load)."""

    def __init__(self, exported, params, n_inputs=None):
        self._exported = exported
        self._params = params
        # recorded at save time; older artifacts derive it from the
        # export signature (inputs precede params in in_avals)
        self._n_inputs = (
            n_inputs if n_inputs is not None
            else len(exported.in_avals) - len(params)
        )

    def __call__(self, *args):
        vals = [a._value if isinstance(a, Tensor) else np.asarray(a) for a in args]
        out = self._exported.call(*vals, *self._params)
        if isinstance(out, (list, tuple)):
            outs = [Tensor(o) for o in out]
            return outs[0] if len(outs) == 1 else outs
        return Tensor(out)

    def forward(self, *args):
        return self(*args)

    def eval(self):
        return self


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save: StableHLO-exported program + params.

    Writes ``path.pdmodel`` (serialized jax.export artifact; the
    reference's ProgramDesc analog) and ``path.pdiparams`` (params npz).
    """
    from ..nn.layer.layers import Layer
    from ..static import InputSpec

    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")
    if input_spec is None:
        raise ValueError("jit.save requires input_spec on this backend")

    example_args = []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            shape = [1 if (s is None or s < 0) else s for s in spec.shape]
            from ..core.dtype import to_jax_dtype
            import jax.numpy as jnp

            example_args.append(jnp.zeros(shape, to_jax_dtype(spec.dtype)))
        elif isinstance(spec, Tensor):
            example_args.append(spec._value)
        else:
            example_args.append(np.asarray(spec))

    params = [p for _, p in layer.named_parameters()]
    buffers = [b for _, b in layer.named_buffers()]
    layer.eval()

    fwd = layer.forward
    if isinstance(fwd, StaticFunction):
        fwd = fwd._function

    def infer_fn(*arg_vals):
        n = len(example_args)
        a_vals = arg_vals[:n]
        p_vals = arg_vals[n:]
        args_t = [Tensor(v) for v in a_vals]
        with autograd.no_grad():
            out, _ = functional_call(
                layer, fwd, args_t, {},
                list(p_vals[: len(params)]),
                list(p_vals[len(params) :]),
            )
        flat, _ = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor)
        )
        return tuple(t._value if isinstance(t, Tensor) else t for t in flat)

    import jax.export as jexport

    jitted = jax.jit(infer_fn)
    exported = jexport.export(jitted)(
        *example_args,
        *[p._value for p in params],
        *[b._value for b in buffers],
    )
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    np.savez(
        path + ".pdiparams",
        __n_inputs__=np.asarray(len(example_args), np.int64),
        **{
            f"p{i}": np.asarray(jax.device_get(p._value))
            for i, p in enumerate(params + buffers)
        },
    )


def load(path, **configs):
    """paddle.jit.load → TranslatedLayer."""
    import jax.export as jexport

    with open(path + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    data = np.load(path + ".pdiparams.npz")
    n_params = len([k for k in data.files if k.startswith("p")])
    params = [data[f"p{i}"] for i in range(n_params)]
    n_inputs = (int(data["__n_inputs__"]) if "__n_inputs__" in data.files
                else None)
    return TranslatedLayer(exported, params, n_inputs=n_inputs)
