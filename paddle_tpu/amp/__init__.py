"""AMP: auto_cast + GradScaler (reference: python/paddle/amp/ — unverified,
SURVEY.md §0).

``auto_cast`` flips a global mode consulted by the dispatch seam: O1 casts
white-listed ops (matmul/conv — the MXU ops) to the amp dtype and keeps
black-listed ops in fp32; O2 casts everything but the black list. On TPU
the natural amp dtype is bfloat16 (no loss scaling needed); fp16 +
GradScaler is kept for API parity.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor
from ..core import autograd

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate", "amp_state"]

WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "addmm", "flash_attention", "scaled_dot_product_attention",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "expm1", "pow", "square",
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "layer_norm", "batch_norm", "instance_norm", "group_norm", "rms_norm",
    "mean", "sum", "cumsum", "logsumexp", "norm", "dist", "cosine_similarity",
    "sigmoid_focal_loss", "bce", "bce_with_logits", "kl_div", "nll_loss",
    "mse_loss", "l1_loss", "smooth_l1",
}


class _AmpState:
    enabled = False
    dtype = jnp.float16
    level = "O1"
    custom_white: set = set()
    custom_black: set = set()


amp_state = _AmpState()


def _known_op_names():
    """Registry names plus bare seam aliases (`functional.relu` → also
    `relu`): AMP lists traditionally use the bare op name."""
    from ..core.dispatch import OP_REGISTRY, SEAM_OPS

    names = set(OP_REGISTRY) | set(SEAM_OPS)
    names.update(n.rsplit(".", 1)[-1] for n in OP_REGISTRY)
    # built-in list entries are valid by definition (some are seam names
    # only recorded at first execution)
    names.update(WHITE_LIST)
    names.update(BLACK_LIST)
    return names


def cast_inputs_for_op(op_name, vals):
    """Called from dispatch.apply when amp is on; casts float arrays."""
    st = amp_state
    white = (op_name in WHITE_LIST or op_name in st.custom_white)
    black = (op_name in BLACK_LIST or op_name in st.custom_black) and not (
        op_name in st.custom_white
    )

    def cast_to(v, dt):
        if hasattr(v, "dtype") and jnp.issubdtype(
            jnp.asarray(v).dtype, jnp.floating
        ):
            if jnp.asarray(v).dtype != dt:
                return jnp.asarray(v).astype(dt)
        return v

    if st.level == "O2":
        if black:
            return [cast_to(v, jnp.float32) for v in vals]
        return [cast_to(v, st.dtype) for v in vals]
    # O1
    if white:
        return [cast_to(v, st.dtype) for v in vals]
    if black:
        return [cast_to(v, jnp.float32) for v in vals]
    return vals


class auto_cast:
    """paddle.amp.auto_cast context manager."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="float16",
                 use_promote=True):
        self._enable = enable
        self._white = set(custom_white_list or ())
        self._black = set(custom_black_list or ())
        self._level = level
        self._dtype = to_jax_dtype(dtype)
        # custom lists key on registered op names (the kernel-registry
        # analog); an unknown name would silently never match — warn.
        # Skip entirely for the plain (no custom lists) hot path.
        unknown = ((self._white | self._black) - _known_op_names()
                   if (self._white or self._black) else ())
        if unknown:
            import warnings

            warnings.warn(
                f"auto_cast: op names not (yet) in the op registry: "
                f"{sorted(unknown)}. A dispatch-seam op name will still "
                f"match once that op runs; check "
                f"paddle.utils.get_registered_ops() for known names.",
                RuntimeWarning,
            )

    def __enter__(self):
        self._saved = (
            amp_state.enabled, amp_state.dtype, amp_state.level,
            amp_state.custom_white, amp_state.custom_black,
        )
        amp_state.enabled = self._enable
        amp_state.dtype = self._dtype
        amp_state.level = self._level
        amp_state.custom_white = self._white
        amp_state.custom_black = self._black
        return self

    def __exit__(self, *exc):
        (
            amp_state.enabled, amp_state.dtype, amp_state.level,
            amp_state.custom_white, amp_state.custom_black,
        ) = self._saved
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="float16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate: O2 casts model params to the amp dtype."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.astype(dtype)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py).

    On TPU-with-bf16 the scale stays 1.0 and this is a pass-through; full
    dynamic scaling is implemented for fp16 parity.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0**15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False  # set by unscale_, cleared by step/update

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        self._unscaled = True
        import numpy as np

        inv = 1.0 / self._scale
        found_inf = False
        for p in optimizer._parameter_list or []:
            if p.grad is not None:
                g = p.grad._value * inv
                p.grad._value = g
        # single fused finiteness check
        import jax

        vals = [
            p.grad._value
            for p in optimizer._parameter_list or []
            if p.grad is not None
        ]
        if vals:
            finite = all(bool(jnp.all(jnp.isfinite(v))) for v in vals)
            found_inf = not finite
        self._found_inf = found_inf

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)  # no-op if the user already unscaled
        self._unscaled = False
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)
