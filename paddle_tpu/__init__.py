"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
API surface, built from scratch on JAX/XLA/Pallas.

``import paddle_tpu as paddle`` is the intended usage: the public names
mirror ``paddle.*`` (see SURVEY.md for the reference component map).
"""
from __future__ import annotations

import os as _os

# Multi-controller bootstrap MUST precede any backend use (jax.devices,
# device_put, ...), and importing the framework touches the backend —
# so a launched worker rendezvouses here, at import. The PJRT
# coordination service replaces the reference's TCPStore (SURVEY.md
# §2.3 TCPStore row — unverified). Gated on the launcher-private marker:
# subprocesses that merely INHERIT the public PADDLE_* vars must not try
# to join the rendezvous as a duplicate process.
if _os.environ.get("PADDLE_TPU_LAUNCHED") == "1":
    from ._bootstrap import rendezvous_from_env as _rdv

    _rdv()

from .version import __version__

# core
from .core.tensor import Tensor, Parameter, to_tensor
from .core.autograd import (
    no_grad,
    enable_grad,
    set_grad_enabled,
    is_grad_enabled,
    grad,
)
from .core.dtype import (
    DType, dtype, bfloat16, float16, float32, float64, int8, int16, int32,
    int64, uint8, bool_ as bool8, complex64, complex128, float8_e4m3fn,
    float8_e5m2, get_default_dtype, set_default_dtype, finfo, iinfo,
)
from .core.place import (
    CPUPlace, TPUPlace, CUDAPlace, XPUPlace, CustomPlace,
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_rocm,
    is_compiled_with_xpu, is_compiled_with_custom_device,
)
from .core.random import seed, get_rng_state, set_rng_state
from .core.flags import set_flags, get_flags

# the op corpus (also patches Tensor methods)
from .tensor import *  # noqa: F401,F403
from . import tensor as tensor  # noqa: PLC0414

# `paddle.bool` is the dtype; paddle shadows the builtin here and so do we.
bool = bool8

_static_mode = False


def disable_static():
    global _static_mode
    _static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def in_dynamic_mode():
    return not _static_mode


def device_count():
    import jax

    return len(jax.devices())


def get_cudnn_version():
    return None


class batch:
    """paddle.batch generator wrapper (legacy reader API)."""

    def __init__(self, reader, batch_size, drop_last=False):
        self.reader, self.batch_size, self.drop_last = reader, batch_size, drop_last

    def __call__(self):
        buf = []
        for item in self.reader():
            buf.append(item)
            if len(buf) == self.batch_size:
                yield buf
                buf = []
        if buf and not self.drop_last:
            yield buf


# Subsystem namespaces (populated progressively; each mirrors paddle.<ns>).
from . import autograd  # noqa: E402
from . import nn  # noqa: E402
from .nn.layer.layers import ParamAttr  # noqa: E402
from . import optimizer  # noqa: E402
from .optimizer.optimizer import L1Decay, L2Decay  # noqa: E402
from . import regularizer  # noqa: E402
from . import amp  # noqa: E402
from . import io  # noqa: E402
from . import jit  # noqa: E402
from . import static  # noqa: E402
from .framework.io import save, load  # noqa: E402
from . import framework  # noqa: E402
from . import metric  # noqa: E402
from . import vision  # noqa: E402
from .hapi.model import Model  # noqa: E402
from . import hapi  # noqa: E402
from . import callbacks  # noqa: E402
from .hapi.summary import summary, flops  # noqa: E402
from . import incubate  # noqa: E402
from . import inference  # noqa: E402
from . import nlp  # noqa: E402
from . import serving  # noqa: E402
from . import profiler  # noqa: E402
from . import fft  # noqa: E402
from . import quantization  # noqa: E402
from . import peft  # noqa: E402
from . import sparse  # noqa: E402
from . import device  # noqa: E402
from . import visualdl  # noqa: E402
from . import distribution  # noqa: E402
from . import signal  # noqa: E402
from . import geometric  # noqa: E402
from . import audio  # noqa: E402
from . import text  # noqa: E402
from . import utils  # noqa: E402
from . import sysconfig  # noqa: E402

# populate the kernel-registry analog once the whole surface exists
from .core.dispatch import (  # noqa: E402
    OP_REGISTRY, register_op, populate_op_registry as _pop_reg,
)

_pop_reg()


def __getattr__(name):
    # lazy: paddle.distributed / paddle.DataParallel must not import the
    # distributed stack (and touch the backend bootstrap) at package
    # import time
    if name == "distributed":
        from . import distributed

        return distributed
    if name == "DataParallel":
        from .distributed.parallel import DataParallel

        return DataParallel
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def __dir__():
    # lazy __getattr__ names must be discoverable (dir() feeds the API
    # manifest generator and user introspection)
    return sorted(set(globals()) | {"distributed", "DataParallel"})
