"""paddle.optimizer namespace."""
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adamax, Adagrad, Adadelta,
    RMSProp, Lamb, LarsMomentum, Rprop, NAdam, RAdam, ASGD,
    LBFGS,
)
from . import lr  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)
from .optimizer import L1Decay, L2Decay  # noqa: F401
