"""Optimizers (reference surface: python/paddle/optimizer/ — unverified,
SURVEY.md §0).

Design: each optimizer defines a pure per-tensor ``_update(p, g, state,
lr)`` rule; ``step()`` runs ONE jitted multi-tensor update over all
params/grads/accumulators — the TPU-native analog of the reference's
``fused_adam`` multi-tensor kernels (paddle/phi/kernels/fused_adam_kernel
— a single compiled XLA program updates every parameter). The same pure
rule is reused by the distributed trainer through
``functional_state_init`` / ``functional_apply``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, Parameter
from ..core import autograd
from .lr import LRScheduler
from .clip import ClipGradBase

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
    "Adadelta", "RMSProp", "Lamb", "LarsMomentum", "Rprop", "NAdam",
    "RAdam", "ASGD", "LBFGS",
]


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


def _wd_coeff(weight_decay):
    if weight_decay is None:
        return 0.0, "l2"
    if isinstance(weight_decay, L2Decay):
        return weight_decay.coeff, "l2"
    if isinstance(weight_decay, L1Decay):
        return weight_decay.coeff, "l1"
    return float(weight_decay), "l2"


class Optimizer:
    _decoupled_wd = False  # AdamW-style

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None, **kwargs):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._wd, self._wd_kind = _wd_coeff(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._states: dict[int, dict] = {}
        self._step_count = 0
        self._jit_cache: dict = {}

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler):
        self._lr = scheduler

    # -- state ---------------------------------------------------------------
    def _init_state(self, p_value):
        """Return dict of accumulator arrays for one param (pure)."""
        return {}

    def _update(self, p, g, state, lr, step, decay=True):
        """Pure per-tensor update: returns (new_p, new_state)."""
        raise NotImplementedError

    def _decay_enabled(self, param) -> bool:
        """Per-param weight-decay gate (AdamW apply_decay_param_fun etc.)."""
        return True

    def _state_for(self, param):
        key = id(param)
        if key not in self._states:
            st = self._init_state(param._value)
            if self._multi_precision and param._value.dtype in (
                jnp.float16, jnp.bfloat16
            ):
                st["master"] = param._value.astype(jnp.float32)
            self._states[key] = st
        return self._states[key]

    # -- functional bridge (used by fleet/hapi jitted train steps) ----------
    def functional_state_init(self, params_tree):
        """Pytree of param arrays → pytree of state dicts (incl. master
        weights for low-precision params when multi_precision)."""

        def init(p):
            st = self._init_state(p)
            if self._multi_precision and p.dtype in (jnp.float16, jnp.bfloat16):
                st["master"] = p.astype(jnp.float32)
            return st

        return jax.tree_util.tree_map(
            init, params_tree,
            is_leaf=lambda x: isinstance(x, jax.Array),
        )

    def functional_apply(self, params_tree, grads_tree, states_tree, lr, step,
                         decay_flags=None):
        """Pure pytree update (no Tensor objects) for jitted trainers.

        ``decay_flags``: optional pytree of bools (same structure) marking
        which params receive weight decay (the eager path derives this
        from ``param.no_weight_decay``/bias detection via _decay_enabled).
        """
        flat_p, tdef = jax.tree_util.tree_flatten(
            params_tree, is_leaf=lambda x: isinstance(x, jax.Array)
        )
        flat_g = tdef.flatten_up_to(grads_tree)
        flat_s = tdef.flatten_up_to(states_tree)
        if decay_flags is None:
            flat_d = [True] * len(flat_p)
        else:
            flat_d = tdef.flatten_up_to(decay_flags)

        def upd(p, g, st, d=True):
            return self._apply_one(p, g, st, lr, step, decay=d)
        if self._grad_clip is not None:
            flat_g = self._grad_clip.clip_values(flat_g)
        new = [upd(p, g, st, d)
               for p, g, st, d in zip(flat_p, flat_g, flat_s, flat_d)]
        new_p = jax.tree_util.tree_unflatten(tdef, [x[0] for x in new])
        new_s = jax.tree_util.tree_unflatten(tdef, [x[1] for x in new])
        return new_p, new_s

    def _apply_one(self, p, g, state, lr, step, decay=True):
        """Full per-tensor update incl. weight decay + master weights."""
        work = state.get("master", p)
        g = g.astype(work.dtype)
        if self._wd and not self._decoupled_wd and decay:
            if self._wd_kind == "l2":
                g = g + self._wd * work
            else:
                g = g + self._wd * jnp.sign(work)
        new_work, new_state = self._update(
            work, g, {k: v for k, v in state.items() if k != "master"},
            lr, step, decay=decay,
        )
        if self._wd and self._decoupled_wd and decay:
            new_work = new_work - lr * self._wd * work
        if "master" in state:
            new_state["master"] = new_work
            return new_work.astype(p.dtype), new_state
        return new_work, new_state

    # -- eager step ----------------------------------------------------------
    @autograd.no_grad()
    def step(self):
        params = [
            p
            for p in (self._parameter_list or [])
            if p.trainable and p.grad is not None
        ]
        if not params:
            return
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step_no = jnp.asarray(self._step_count + 1, jnp.int32)
        p_vals = [p._value for p in params]
        g_vals = [p.grad._value for p in params]
        s_vals = [self._state_for(p) for p in params]
        decay_flags = [self._decay_enabled(p) for p in params]

        # Params may live on disjoint device sets (pipeline stages); a
        # single XLA program cannot span them, so fuse per device set.
        # Grad clipping with a GLOBAL norm must still see every grad, so
        # the squared-norm is reduced across groups first.
        def _devset(v):
            try:
                return tuple(sorted(d.id for d in v.sharding.device_set))
            except Exception:
                return ("default",)

        groups: dict = {}
        for i, v in enumerate(p_vals):
            groups.setdefault(_devset(v), []).append(i)

        # Global-norm clipping across multiple device sets: reduce the
        # squared norms per group, combine on host, feed the scale in as a
        # traced scalar so in-group clipping is skipped.
        from .clip import ClipGradByGlobalNorm

        gscale = None
        if isinstance(self._grad_clip, ClipGradByGlobalNorm) and len(groups) > 1:
            import numpy as _np

            # eager reductions (no jit: would retrace every step via the
            # fresh closure; a handful of per-group reductions is cheap)
            sq = 0.0
            for devset, idxs in groups.items():
                sq += float(
                    sum(
                        jnp.sum(jnp.square(g_vals[i].astype(jnp.float32)))
                        for i in idxs
                    )
                )
            global_norm = float(_np.sqrt(sq))
            clip_norm = self._grad_clip.clip_norm
            gscale = jnp.asarray(
                clip_norm / max(global_norm, clip_norm), jnp.float32
            )

        for devset, idxs in groups.items():
            sub_decay = tuple(decay_flags[i] for i in idxs)
            shapes = tuple((p_vals[i].shape, str(p_vals[i].dtype)) for i in idxs)
            cache_key = (devset, shapes, sub_decay, gscale is not None)
            if cache_key not in self._jit_cache:
                def fused(ps, gs, ss, lr_, st_, gscale_, _decay=sub_decay):
                    if gscale_ is not None:
                        gs = [
                            (g.astype(jnp.float32) * gscale_).astype(g.dtype)
                            for g in gs
                        ]
                    elif self._grad_clip is not None:
                        gs = self._grad_clip.clip_values(gs)
                    outs = [
                        self._apply_one(p, g, s, lr_, st_, decay=d)
                        for p, g, s, d in zip(ps, gs, ss, _decay)
                    ]
                    return [o[0] for o in outs], [o[1] for o in outs]

                self._jit_cache[cache_key] = jax.jit(
                    fused, static_argnames=()
                )
            jitted = self._jit_cache[cache_key]
            new_p, new_s = jitted(
                [p_vals[i] for i in idxs],
                [g_vals[i] for i in idxs],
                [s_vals[i] for i in idxs],
                lr, step_no, gscale,
            )
            for j, i in enumerate(idxs):
                params[i]._value = new_p[j]
                self._states[id(params[i])] = new_s[j]
        self._step_count += 1

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    # -- serialization -------------------------------------------------------
    def state_dict(self):
        out = {"step_count": self._step_count}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        for i, p in enumerate(self._parameter_list or []):
            st = self._states.get(id(p))
            if st:
                for k, v in st.items():
                    out[f"{p.name}_{k}"] = Tensor(v)
        return out

    def set_state_dict(self, state_dict):
        self._step_count = state_dict.get("step_count", 0)
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state_dict:
            self._lr.set_state_dict(dict(state_dict["LR_Scheduler"]))
        missing = []
        for p in self._parameter_list or []:
            st = self._state_for(p)
            for k in list(st):
                key = f"{p.name}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    st[k] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                else:
                    missing.append(key)
        if missing:
            # id-based fallback names differ across processes — a silent
            # skip would reset accumulators to zero on resume
            import warnings

            warnings.warn(
                f"optimizer.set_state_dict: {len(missing)} accumulator keys "
                f"not found in the checkpoint (e.g. {missing[0]!r}); those "
                "accumulators keep their current values. Name parameters via "
                "Layer.create_parameter for stable keys.",
                RuntimeWarning,
            )


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _update(self, p, g, state, lr, step, decay=True):
        return p - lr.astype(p.dtype) * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, p_value):
        return {"velocity": jnp.zeros(p_value.shape, jnp.float32)}

    def _update(self, p, g, state, lr, step, decay=True):
        v = self._momentum * state["velocity"] + g.astype(jnp.float32)
        if self._nesterov:
            upd = g.astype(jnp.float32) + self._momentum * v
        else:
            upd = v
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), {"velocity": v}


class Adam(Optimizer):
    """``moment_dtype="bfloat16"`` stores both moments in bf16 (HBM halved
    for optimizer state — on one 16G v5e chip this is what lets a ~1B
    model train WITHOUT activation recompute; the update math stays f32)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, moment_dtype="float32", name=None,
                 **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        try:
            md = jnp.dtype(
                jnp.bfloat16 if moment_dtype in ("bf16",) else moment_dtype
            )
        except TypeError:
            md = None
        if md not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
            raise ValueError(
                f"moment_dtype must be float32 or bfloat16, got {moment_dtype!r}"
            )
        self._moment_dtype = md

    def _init_state(self, p_value):
        return {
            "moment1": jnp.zeros(p_value.shape, self._moment_dtype),
            "moment2": jnp.zeros(p_value.shape, self._moment_dtype),
        }

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        m = (self._beta1 * state["moment1"].astype(jnp.float32)
             + (1 - self._beta1) * g32)
        v = (self._beta2 * state["moment2"].astype(jnp.float32)
             + (1 - self._beta2) * jnp.square(g32))
        t = step.astype(jnp.float32)
        m_hat = m / (1 - self._beta1**t)
        v_hat = v / (1 - self._beta2**t)
        new_p = p.astype(jnp.float32) - lr * m_hat / (jnp.sqrt(v_hat) + self._eps)
        md = self._moment_dtype
        return new_p.astype(p.dtype), {
            "moment1": m.astype(md), "moment2": v.astype(md),
        }


class AdamW(Adam):
    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False,
                 moment_dtype="float32", name=None, **kwargs):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         moment_dtype=moment_dtype)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_enabled(self, param) -> bool:
        if self._apply_decay_param_fun is None:
            return True
        return bool(self._apply_decay_param_fun(param.name))


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, p_value):
        return {
            "moment": jnp.zeros(p_value.shape, jnp.float32),
            "inf_norm": jnp.zeros(p_value.shape, jnp.float32),
        }

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32))
        t = step.astype(jnp.float32)
        new_p = p.astype(jnp.float32) - (lr / (1 - self._beta1**t)) * m / (u + self._eps)
        return new_p.astype(p.dtype), {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p_value):
        return {"moment": jnp.full(p_value.shape, self._init_acc, jnp.float32)}

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        acc = state["moment"] + jnp.square(g32)
        new_p = p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(acc) + self._eps)
        return new_p.astype(p.dtype), {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._eps, self._rho = epsilon, rho

    def _init_state(self, p_value):
        return {
            "avg_squared_grad": jnp.zeros(p_value.shape, jnp.float32),
            "avg_squared_update": jnp.zeros(p_value.shape, jnp.float32),
        }

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * jnp.square(g32)
        upd = (
            jnp.sqrt(state["avg_squared_update"] + self._eps)
            / jnp.sqrt(asg + self._eps)
        ) * g32
        asu = self._rho * state["avg_squared_update"] + (1 - self._rho) * jnp.square(upd)
        new_p = p.astype(jnp.float32) - lr * upd
        return new_p.astype(p.dtype), {
            "avg_squared_grad": asg,
            "avg_squared_update": asu,
        }


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, p_value):
        st = {
            "mean_square": jnp.zeros(p_value.shape, jnp.float32),
            "momentum_acc": jnp.zeros(p_value.shape, jnp.float32),
        }
        if self._centered:
            st["mean_grad"] = jnp.zeros(p_value.shape, jnp.float32)
        return st

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(g32)
        new_state = {"mean_square": ms}
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum_acc"] + lr * g32 / denom
        new_state["momentum_acc"] = mom
        new_p = p.astype(jnp.float32) - mom
        return new_p.astype(p.dtype), new_state


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lamb_wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p_value):
        return {
            "moment1": jnp.zeros(p_value.shape, jnp.float32),
            "moment2": jnp.zeros(p_value.shape, jnp.float32),
        }

    def _decay_enabled(self, param) -> bool:
        if self._exclude_fn is None:
            return True
        return not bool(self._exclude_fn(param))

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        m_hat = m / (1 - self._beta1**t)
        v_hat = v / (1 - self._beta2**t)
        wd = self._lamb_wd if decay else 0.0
        r = m_hat / (jnp.sqrt(v_hat) + self._eps) + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where(
            (w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0
        )
        new_p = p32 - lr * trust * r
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}


class LarsMomentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, False, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay

    def _init_state(self, p_value):
        return {"velocity": jnp.zeros(p_value.shape, jnp.float32)}

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        w_norm = jnp.linalg.norm(p32)
        g_norm = jnp.linalg.norm(g32)
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + self._lars_wd * w_norm + 1e-12),
            1.0,
        )
        v = self._momentum * state["velocity"] + local_lr * lr * (
            g32 + self._lars_wd * p32
        )
        return (p32 - v).astype(p.dtype), {"velocity": v}


class Rprop(Optimizer):
    """Resilient backprop (reference paddle.optimizer.Rprop): per-weight
    step sizes grown/shrunk by the sign agreement of successive grads."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._eta_minus, self._eta_plus = etas
        self._lr_min, self._lr_max = learning_rate_range

    def _init_state(self, p_value):
        return {
            "prev_grad": jnp.zeros(p_value.shape, jnp.float32),
            "step_size": jnp.full(p_value.shape, self.get_lr(),
                                  jnp.float32),
        }

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        sign = jnp.sign(g32 * state["prev_grad"])
        grow = jnp.where(sign > 0, self._eta_plus,
                         jnp.where(sign < 0, self._eta_minus, 1.0))
        step_size = jnp.clip(state["step_size"] * grow,
                             self._lr_min, self._lr_max)
        # on sign flip: revert grad (classic Rprop-): no step this round
        g_eff = jnp.where(sign < 0, 0.0, g32)
        new_p = p.astype(jnp.float32) - jnp.sign(g_eff) * step_size
        return new_p.astype(p.dtype), {
            "prev_grad": g_eff, "step_size": step_size,
        }


class NAdam(Adam):
    """Adam with Nesterov momentum and the reference's mu_t momentum-decay
    schedule (paddle.optimizer.NAdam, momentum_decay=0.004)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kwargs):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, **kwargs)
        self._psi = float(momentum_decay)

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        t = step.astype(jnp.float32)
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_next = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        # running products of mu (closed form since mu depends on t only)
        # approximate prod via stored scalar is avoided: use the paddle
        # recurrences with mu products tracked in state
        mu_prod = state.get(
            "mu_prod", jnp.ones((), jnp.float32)) * mu_t
        m = self._beta1 * state["moment1"].astype(jnp.float32) \
            + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"].astype(jnp.float32) \
            + (1 - self._beta2) * jnp.square(g32)
        m_hat = (mu_next * m / (1 - mu_prod * mu_next)
                 + (1 - mu_t) * g32 / (1 - mu_prod))
        v_hat = v / (1 - self._beta2 ** t)
        new_p = p.astype(jnp.float32) - lr * m_hat / (
            jnp.sqrt(v_hat) + self._eps)
        md = self._moment_dtype
        return new_p.astype(p.dtype), {
            "moment1": m.astype(md), "moment2": v.astype(md),
            "mu_prod": mu_prod,
        }

    def _init_state(self, p_value):
        st = super()._init_state(p_value)
        st["mu_prod"] = jnp.ones((), jnp.float32)
        return st


class RAdam(Adam):
    """Rectified Adam (reference paddle.optimizer.RAdam): warms up the
    adaptive term by the variance-rectification factor."""

    def _update(self, p, g, state, lr, step, decay=True):
        g32 = g.astype(jnp.float32)
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"].astype(jnp.float32) + (1 - b1) * g32
        v = b2 * state["moment2"].astype(jnp.float32) + (1 - b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        m_hat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1.0
        rho_t = rho_inf - 2.0 * t * b2 ** t / (1 - b2 ** t)
        r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                     / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                   1e-12))
        v_hat = jnp.sqrt(v / (1 - b2 ** t))
        adaptive = lr * r * m_hat / (v_hat + self._eps)
        plain = lr * m_hat
        new_p = p.astype(jnp.float32) - jnp.where(rho_t > 4.0, adaptive,
                                                  plain)
        md = self._moment_dtype
        return new_p.astype(p.dtype), {
            "moment1": m.astype(md), "moment2": v.astype(md),
        }


class ASGD(Optimizer):
    """Averaged SGD (reference paddle.optimizer.ASGD): SGD steps plus a
    running parameter average stored alongside the state."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._batch_num = max(int(batch_num), 1)

    def _init_state(self, p_value):
        return {
            "d": jnp.zeros(p_value.shape, jnp.float32),  # rolling grad sum
            "y": jnp.zeros(p_value.shape, jnp.float32),  # grad replaced
        }

    def _update(self, p, g, state, lr, step, decay=True):
        # reference recurrence: d <- d - y + g; y <- g; p -= lr * d / n
        g32 = g.astype(jnp.float32)
        d = state["d"] - state["y"] + g32
        n = jnp.minimum(step.astype(jnp.float32), float(self._batch_num))
        new_p = p.astype(jnp.float32) - lr * d / jnp.maximum(n, 1.0)
        return new_p.astype(p.dtype), {"d": d, "y": g32}


class LBFGS(Optimizer):
    """L-BFGS with closure-based step (reference paddle.optimizer.LBFGS).

    ``step(closure)`` re-evaluates loss+grads; the two-loop recursion
    over the last ``history_size`` (s, y) pairs runs as fused jnp ops on
    flattened parameters."""

    def __init__(self, learning_rate=1.0, max_iter=1, history_size=10,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 parameters=None, line_search_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, None, False, name)
        self.max_iter = max_iter
        self.history_size = history_size
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self._hist = []  # list of (s, y, rho) flattened
        self._prev = None  # (flat_params, flat_grad)

    def _flat(self, vals):
        return jnp.concatenate([v.astype(jnp.float32).reshape(-1)
                                for v in vals])

    def _unflat(self, flat, params):
        # must walk the SAME param subset the flat vector was built from
        # (frozen/no-grad params are excluded by step)
        out, off = [], 0
        for p in params:
            n = int(np.prod(p._value.shape)) if p._value.ndim else 1
            out.append(flat[off: off + n].reshape(p._value.shape))
            off += n
        return out

    def _direction(self, q):
        alphas = []
        for s, y, rho in reversed(self._hist):
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append(a)
        if self._hist:
            s, y, _ = self._hist[-1]
            q = q * (jnp.dot(s, y) / jnp.maximum(jnp.dot(y, y), 1e-12))
        for (s, y, rho), a in zip(self._hist, reversed(alphas)):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        return q

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure")
        loss = None
        for _ in range(self.max_iter):
            loss = closure()
            params = [p for p in (self._parameter_list or [])
                      if p.grad is not None]
            if not params:
                return loss
            flat_g = self._flat([p.grad._value for p in params])
            flat_p = self._flat([p._value for p in params])
            if float(jnp.max(jnp.abs(flat_g))) <= self.tol_grad:
                break
            if self._prev is not None:
                # curvature pair from the PREVIOUS accepted step
                s = flat_p - self._prev[0]
                y = flat_g - self._prev[1]
                sy = float(jnp.dot(s, y))
                if sy > 1e-10:
                    self._hist.append((s, y, 1.0 / sy))
                    if len(self._hist) > self.history_size:
                        self._hist.pop(0)
            # record the CURRENT point before stepping away from it
            self._prev = (flat_p, flat_g)
            d = -self._direction(flat_g)
            lr = self.get_lr()
            step_vec = lr * d
            if float(jnp.max(jnp.abs(step_vec))) <= self.tol_change:
                break
            new_flat = flat_p + step_vec
            for p, v in zip(params, self._unflat(new_flat, params)):
                p._value = v.astype(p._value.dtype)
        return loss

    def state_dict(self):
        out = super().state_dict()
        out["lbfgs_hist"] = [
            (np.asarray(s), np.asarray(y), r) for s, y, r in self._hist
        ]
        if self._prev is not None:
            out["lbfgs_prev"] = tuple(np.asarray(v) for v in self._prev)
        return out

    def set_state_dict(self, state):
        super().set_state_dict(state)
        self._hist = [
            (jnp.asarray(s), jnp.asarray(y), r)
            for s, y, r in state.get("lbfgs_hist", [])
        ]
        prev = state.get("lbfgs_prev")
        self._prev = tuple(jnp.asarray(v) for v in prev) if prev else None
