"""Gradient clipping (reference: python/paddle/nn/clip.py — unverified,
SURVEY.md §0). Clips operate on (param, grad) value lists inside the
jitted update, multi-tensor style."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def clip_values(self, grads):
        """grads: list of jax arrays → clipped list (used inside jit)."""
        raise NotImplementedError

    def __call__(self, params_grads):
        """Eager API: list of (param Tensor, grad Tensor) pairs."""
        from ..core.tensor import Tensor

        grads = [g._value for _, g in params_grads]
        clipped = self.clip_values(grads)
        return [
            (p, Tensor(g, stop_gradient=True))
            for (p, _), g in zip(params_grads, clipped)
        ]


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def clip_values(self, grads):
        return [jnp.clip(g, self.min, self.max) for g in grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def clip_values(self, grads):
        out = []
        for g in grads:
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.where(
                norm > self.clip_norm, self.clip_norm / jnp.maximum(norm, 1e-12), 1.0
            )
            out.append((g.astype(jnp.float32) * scale).astype(g.dtype))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def clip_values(self, grads):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(g.astype(jnp.float32) * scale).astype(g.dtype) for g in grads]
