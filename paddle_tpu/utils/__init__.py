"""paddle.utils (reference: python/paddle/utils/ — unverified,
SURVEY.md §0): install check + misc helpers."""
from __future__ import annotations

import sys

__all__ = ["run_check", "try_import", "unique_name"]


def run_check():
    """The classic install smoke test (reference paddle.utils.run_check):
    runs a small matmul forward+backward on the current device and, when
    more devices are visible, a sharded matmul over the mesh."""
    import numpy as np
    import jax

    import paddle_tpu as paddle

    dev = paddle.get_device()
    print(f"Running verify PaddlePaddle(TPU-native) program on {dev} ...")
    x = paddle.to_tensor(np.random.rand(16, 32).astype("float32"))
    x.stop_gradient = False
    w = paddle.to_tensor(np.random.rand(32, 8).astype("float32"))
    w.stop_gradient = False
    loss = (x @ w).sum()
    loss.backward()
    assert x.grad is not None and w.grad is not None
    n = len(jax.devices())
    if n > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        xs = jax.device_put(
            x._value, NamedSharding(mesh, PartitionSpec("dp", None)))
        (xs @ w._value).sum().block_until_ready()
        print(f"PaddlePaddle(TPU-native) works well on {n} devices.")
    print(
        "PaddlePaddle(TPU-native) is installed successfully! "
        "Let's start deep learning with PaddlePaddle now."
    )


def try_import(module_name, err_msg=None):
    """Import a module with a friendly error (reference
    paddle.utils.try_import)."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed"
        ) from e


class _UniqueNames:
    def __init__(self):
        self._counters = {}

    def generate(self, key="tmp"):
        i = self._counters.get(key, 0)
        self._counters[key] = i + 1
        return f"{key}_{i}"


unique_name = _UniqueNames()


def get_registered_ops():
    """Names in the op registry (the reference's get_all_op_names analog:
    phi kernel registry — SURVEY.md §2.1, unverified). Includes the
    public ``paddle.*``/``functional.*`` surface registered at import and
    dispatch-seam op names recorded at first execution (name-only)."""
    from ..core.dispatch import OP_REGISTRY, SEAM_OPS

    return sorted(set(OP_REGISTRY) | SEAM_OPS)


def get_op_callable(name):
    """The python callable registered for ``name`` (KeyError if absent)."""
    from ..core.dispatch import OP_REGISTRY

    return OP_REGISTRY[name]


__all__ += ["get_registered_ops", "get_op_callable"]
