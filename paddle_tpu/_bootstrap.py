"""Multi-controller rendezvous helper (import-light: safe to call before
any backend use). One copy of the launcher env protocol, shared by the
package-import bootstrap and ``distributed.init_parallel_env``."""
from __future__ import annotations

import os

# set by paddle.distributed.launch for its OWN workers; the public
# PADDLE_* vars alone must not trigger a rendezvous in arbitrary
# subprocesses that merely inherit them (they would join as a duplicate
# process_id and hang)
LAUNCHER_MARKER = "PADDLE_TPU_LAUNCHED"


def rendezvous_from_env():
    """jax.distributed.initialize from the PADDLE_* env protocol.

    Returns True if a rendezvous was performed. No-op when the env does
    not describe a multi-process job or the coordination client already
    exists."""
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n <= 1:
        return False
    import jax
    from jax._src import distributed as _dist

    if getattr(_dist.global_state, "client", None) is not None:
        return False
    coordinator = os.environ.get("PADDLE_MASTER") or os.environ.get(
        "MASTER_ADDR", "127.0.0.1:8701"
    )
    # consume the marker BEFORE initializing: grandchild processes that
    # inherit the env must not try to join as duplicate process_ids
    os.environ.pop(LAUNCHER_MARKER, None)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=n,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
        )
    except RuntimeError as e:
        if "must be called before" in str(e):
            raise RuntimeError(
                "multi-process rendezvous requires the PADDLE_* env to be "
                "set BEFORE `import paddle_tpu` (importing touches the "
                "XLA backend). Use paddle.distributed.launch, or export "
                "the env first."
            ) from e
        raise
    return True
