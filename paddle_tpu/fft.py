"""paddle.fft — discrete Fourier transforms (reference:
python/paddle/fft.py — unverified, SURVEY.md §0).

Thin dispatch-seam wrappers over ``jnp.fft``: XLA lowers FFTs natively
(TPU executes them on the VPU), and routing through ``apply`` gives the
tape autograd + AMP/nan-check for free. ``norm`` semantics follow the
reference ("backward" | "ortho" | "forward"), which match numpy's.
When the active accelerator backend lacks complex-dtype support (the
axon TPU tunnel does; full XLA:TPU does not), transforms are offloaded
to the host CPU backend eagerly — correct but not accelerator-speed; a
clear error is raised if such an FFT is traced inside ``jit``.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .tensor._helpers import apply, ensure_tensor, axes_arg

_COMPLEX_OK = None


def _complex_supported():
    # Static platform rule — a *failed* complex op poisons the axon
    # runtime (every later dispatch errors), so probing is not an option.
    # cpu/gpu XLA backends have full complex support; the tunneled TPU
    # backend here has none, so TPU routes to the host fallback.
    global _COMPLEX_OK
    if _COMPLEX_OK is None:
        _COMPLEX_OK = jax.default_backend() in ("cpu", "gpu", "cuda", "rocm")
    return _COMPLEX_OK


def _host_fft(np_fn, v, **kw):
    """Run the transform on the host CPU backend; the result lives on the
    cpu device (real-valued results transfer back transparently)."""
    if isinstance(v, jax.core.Tracer):
        raise RuntimeError(
            "this backend has no complex-dtype support, so FFT cannot be "
            "traced under jit here; call it eagerly (host-offloaded)"
        )
    out = np_fn(np.asarray(v), **kw)
    dtype = np.complex64 if out.dtype == np.complex128 else (
        np.float32 if out.dtype == np.float64 else out.dtype
    )
    return jax.device_put(out.astype(dtype), jax.devices("cpu")[0])

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(
            f"norm must be 'backward', 'ortho' or 'forward', got {norm!r}"
        )
    return norm


def _wrap1(jnp_fn, op_name):
    np_fn = getattr(np.fft, op_name)

    def op(x, n=None, axis=-1, norm="backward", name=None):
        from .core.tensor import Tensor

        x = ensure_tensor(x)
        nrm = _norm(norm)
        if not _complex_supported():
            # host offload is opaque to the tape: no FFT grads here
            return Tensor(
                _host_fft(np_fn, x._value, n=n, axis=axis, norm=nrm),
                stop_gradient=True,
            )
        return apply(
            lambda v: jnp_fn(v, n=n, axis=axis, norm=nrm), x,
            op_name=op_name,
        )

    op.__name__ = op_name
    op.__doc__ = f"paddle.fft.{op_name}(x, n=None, axis=-1, norm='backward')"
    return op


def _wrap_nd(jnp_fn, op_name, default_axes):
    np_fn = getattr(np.fft, op_name)

    def op(x, s=None, axes=default_axes, norm="backward", name=None):
        from .core.tensor import Tensor

        x = ensure_tensor(x)
        ax = axes_arg(axes)
        nrm = _norm(norm)
        if not _complex_supported():
            return Tensor(
                _host_fft(np_fn, x._value, s=s, axes=ax, norm=nrm),
                stop_gradient=True,
            )
        return apply(
            lambda v: jnp_fn(v, s=s, axes=ax, norm=nrm), x,
            op_name=op_name,
        )

    op.__name__ = op_name
    op.__doc__ = (
        f"paddle.fft.{op_name}(x, s=None, axes={default_axes}, "
        f"norm='backward')"
    )
    return op


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")

fft2 = _wrap_nd(jnp.fft.fft2, "fft2", (-2, -1))
ifft2 = _wrap_nd(jnp.fft.ifft2, "ifft2", (-2, -1))
rfft2 = _wrap_nd(jnp.fft.rfft2, "rfft2", (-2, -1))
irfft2 = _wrap_nd(jnp.fft.irfft2, "irfft2", (-2, -1))
fftn = _wrap_nd(jnp.fft.fftn, "fftn", None)
ifftn = _wrap_nd(jnp.fft.ifftn, "ifftn", None)
rfftn = _wrap_nd(jnp.fft.rfftn, "rfftn", None)
irfftn = _wrap_nd(jnp.fft.irfftn, "irfftn", None)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.dtype import to_jax_dtype

    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        out = out.astype(to_jax_dtype(dtype))
    return apply(lambda: out, op_name="fftfreq")


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.dtype import to_jax_dtype

    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        out = out.astype(to_jax_dtype(dtype))
    return apply(lambda: out, op_name="rfftfreq")


def fftshift(x, axes=None, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axes)
    return apply(lambda v: jnp.fft.fftshift(v, axes=ax), x, op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    x = ensure_tensor(x)
    ax = axes_arg(axes)
    return apply(
        lambda v: jnp.fft.ifftshift(v, axes=ax), x, op_name="ifftshift"
    )
