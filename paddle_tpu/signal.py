"""paddle.signal — STFT / iSTFT (reference: python/paddle/signal.py —
unverified, SURVEY.md §0).

Framing/windowing/overlap-add are real-valued jnp ops on the tape; the
DFT itself routes through ``paddle.fft`` (which host-offloads on
backends without complex support — see fft.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .tensor._helpers import apply, ensure_tensor
from . import fft as _fft

__all__ = ["stft", "istft"]


def _frame(x, frame_length, hop_length):
    """(..., T) → (..., n_frames, frame_length)."""
    n = (x.shape[-1] - frame_length) // hop_length + 1
    idx = (jnp.arange(n)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    return x[..., idx]


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Returns (..., n_fft//2 + 1, n_frames) complex (onesided) like the
    reference."""
    x = ensure_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = ensure_tensor(window)

    def padded_window(w, dtype):
        # reference: window=None is a RECTANGULAR window of win_length,
        # zero-padded and centered in the n_fft frame
        if w is None:
            w = jnp.ones((win_length,), dtype)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            w = jnp.pad(w, (lp, n_fft - win_length - lp))
        return w

    def prep(v, *maybe_w):
        if center:
            pad = n_fft // 2
            v = jnp.pad(
                v, [(0, 0)] * (v.ndim - 1) + [(pad, pad)], mode=pad_mode
            )
        frames = _frame(v, n_fft, hop_length)  # (..., n_frames, n_fft)
        frames = frames * padded_window(
            maybe_w[0] if maybe_w else None, frames.dtype
        )
        if normalized:
            frames = frames / jnp.sqrt(jnp.asarray(n_fft, frames.dtype))
        return frames

    args = [x] + ([window] if window is not None else [])
    frames = apply(prep, *args, op_name="stft_frames")
    spec = (_fft.rfft(frames, axis=-1) if onesided
            else _fft.fft(frames, axis=-1))
    # (..., n_frames, F) → (..., F, n_frames)
    perm = list(range(spec.ndim))
    perm[-1], perm[-2] = perm[-2], perm[-1]
    return spec.transpose(perm)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    x = ensure_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if return_complex and onesided:
        raise ValueError(
            "istft: return_complex=True requires onesided=False"
        )
    perm = list(range(x.ndim))
    perm[-1], perm[-2] = perm[-2], perm[-1]
    spec = x.transpose(perm)  # (..., n_frames, F)
    if onesided:
        frames = _fft.irfft(spec, n=n_fft, axis=-1)
    else:
        cframes = _fft.ifft(spec, axis=-1)
        frames = cframes if return_complex else cframes.real()
    if window is not None:
        window = ensure_tensor(window)

    def ola(fr, *maybe_w):
        if normalized:
            fr = fr * jnp.sqrt(jnp.asarray(n_fft, fr.dtype))
        w = maybe_w[0] if maybe_w else jnp.ones(
            (win_length,),
            fr.dtype if not jnp.iscomplexobj(fr) else jnp.float32,
        )
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            w = jnp.pad(w, (lp, n_fft - win_length - lp))
        n_frames = fr.shape[-2]
        t_len = n_fft + hop_length * (n_frames - 1)
        out = jnp.zeros(fr.shape[:-2] + (t_len,), fr.dtype)
        norm = jnp.zeros((t_len,), fr.dtype)
        for i in range(n_frames):  # unrolled overlap-add (static frames)
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[..., sl].add(fr[..., i, :] * w)
            norm = norm.at[sl].add(w * w)
        out = out / jnp.maximum(norm, 1e-10)
        if center:
            out = out[..., n_fft // 2: t_len - n_fft // 2]
        return out

    args = [frames] + ([window] if window is not None else [])
    out = apply(ola, *args, op_name="istft_ola")
    if length is not None:
        out = out[..., :length]
    return out
