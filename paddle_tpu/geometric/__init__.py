"""paddle.geometric — graph message passing / segment ops (reference:
python/paddle/geometric/ — unverified, SURVEY.md §0).

Segment reductions map 1:1 onto ``jax.ops.segment_*`` (TPU lowers them
to sorted scatters); message passing (``send_u_recv`` etc.) is
gather-by-src → segment-reduce-by-dst, which XLA fuses. All ops are
taped (differentiable through gather/scatter). Empty segments reduce to
0 for every reduce_op, matching the reference."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor._helpers import apply, ensure_tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv",
]


def _reduce(msgs, ids, n, reduce_op):
    """Shared segment reduction with reference empty-bucket semantics."""
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msgs, ids, num_segments=n)
        cnt = jax.ops.segment_sum(
            jnp.ones(ids.shape, msgs.dtype), ids, num_segments=n
        )
        shape = (-1,) + (1,) * (msgs.ndim - 1)
        return s / jnp.maximum(cnt.reshape(shape), 1)
    jfn = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}[reduce_op]
    out = jfn(msgs, ids, num_segments=n)
    if reduce_op in ("max", "min"):
        # empty buckets come back as +/-inf; the reference zeroes them
        out = jnp.where(jnp.isfinite(out), out, 0.0)
    return out


def _num_segments(ids, given):
    if given is not None:
        return int(given)
    v = ids._value
    return int(jnp.max(v)) + 1 if v.size else 0


def _make_segment(reduce_op):
    def op(data, segment_ids, name=None, num_segments=None):
        data = ensure_tensor(data)
        segment_ids = ensure_tensor(segment_ids)
        n = _num_segments(segment_ids, num_segments)
        return apply(
            lambda d, ids: _reduce(d, ids, n, reduce_op),
            data, segment_ids, op_name=f"segment_{reduce_op}",
        )

    op.__name__ = f"segment_{reduce_op}"
    op.__doc__ = (
        f"paddle.geometric.segment_{reduce_op}(data, segment_ids): "
        f"{reduce_op}-reduce rows into segment buckets."
    )
    return op


segment_sum = _make_segment("sum")
segment_mean = _make_segment("mean")
segment_max = _make_segment("max")
segment_min = _make_segment("min")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather rows of ``x`` at ``src_index``, reduce into ``dst_index``
    buckets (reference paddle.geometric.send_u_recv)."""
    x = ensure_tensor(x)
    src_index = ensure_tensor(src_index)
    dst_index = ensure_tensor(dst_index)
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unsupported reduce_op {reduce_op!r}")
    n = _num_segments(dst_index, out_size)

    def fn(xv, src, dst):
        return _reduce(xv[src], dst, n, reduce_op)

    return apply(fn, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Like send_u_recv but the message combines node features with edge
    features ``y`` first (add/sub/mul/div)."""
    x = ensure_tensor(x)
    y = ensure_tensor(y)
    src_index = ensure_tensor(src_index)
    dst_index = ensure_tensor(dst_index)
    combine = {
        "add": jnp.add, "sub": jnp.subtract,
        "mul": jnp.multiply, "div": jnp.divide,
    }.get(message_op)
    if combine is None:
        raise ValueError(f"unsupported message_op {message_op!r}")
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unsupported reduce_op {reduce_op!r}")
    n = _num_segments(dst_index, out_size)

    def fn(xv, ev, src, dst):
        return _reduce(combine(xv[src], ev), dst, n, reduce_op)

    return apply(fn, x, y, src_index, dst_index, op_name="send_ue_recv")
