"""paddle.incubate namespace (reference: python/paddle/incubate/ —
unverified, SURVEY.md §0/§2.4): fused-op wrappers and experimental
distributed features, TPU-native."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401

__all__ = ["nn", "distributed", "optimizer", "LookAhead",
           "ModelAverage", "ExponentialMovingAverage"]
from . import optimizer  # noqa: E402,F401
from .optimizer import (  # noqa: E402,F401
    LookAhead, ModelAverage, ExponentialMovingAverage,
)
