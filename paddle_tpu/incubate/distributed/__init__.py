"""paddle.incubate.distributed — experimental distributed features
(reference: python/paddle/incubate/distributed/ — unverified,
SURVEY.md §0). MoE lives in .models.moe."""
from . import models  # noqa: F401

__all__ = ["models"]
