"""Mixture-of-Experts with expert parallelism (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py, GlobalScatter/
GlobalGather collective ops — unverified, SURVEY.md §0/§2.3 EP row).

TPU-native design: the GShard einsum formulation. Expert weights are
STACKED (num_experts leading dim) and sharded over an ``expert`` mesh
axis; token dispatch/combine are einsums against one-hot capacity
masks, so GSPMD lowers the dispatch to the same all-to-all the reference
issues explicitly via GlobalScatter — no hand-written collectives.
"""
from .gate import TopKGate, GShardGate, SwitchGate  # noqa: F401
from .moe_layer import MoELayer  # noqa: F401

__all__ = ["MoELayer", "TopKGate", "GShardGate", "SwitchGate"]
