"""MoELayer — expert-parallel FFN mixture (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py — unverified,
SURVEY.md §0/§2.3 EP row).

Experts are a stacked SwiGLU/GELU FFN: weights (num_experts, ...) sharded
over an ``expert`` mesh axis. Dispatch/combine are the GShard einsums —
under a mesh, constraining the dispatched tensor's expert dim makes GSPMD
emit the all-to-all over ICI (the reference's GlobalScatter/GlobalGather
NCCL ops)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer.layers import Layer
from .....nn import initializer as I
from .....tensor._helpers import apply, ensure_tensor
from .....parallel import mesh as mesh_state
from .gate import TopKGate, SwitchGate

__all__ = ["MoELayer"]


class MoELayer(Layer):
    """MoE FFN block.

    Args:
        d_model: token dim.
        d_hidden: expert FFN hidden dim.
        num_experts: global expert count.
        gate: "gshard" | "switch" | a gate object (default top-2).
        activation: "gelu" | "swiglu".
        expert_axis: mesh axis experts shard over (default: "dp" when its
            size divides num_experts, else "mp"; no mesh → serial).
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 activation="gelu", capacity_factor=2.0, expert_axis=None,
                 dispatch_mode="auto", name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.activation = activation
        if isinstance(gate, str):
            gate = {"gshard": TopKGate(2, capacity_factor),
                    "switch": SwitchGate(capacity_factor),
                    "top2": TopKGate(2, capacity_factor)}[gate]
        self.gate = gate
        self.l_aux = None

        ffn1_out = 2 * d_hidden if activation == "swiglu" else d_hidden
        self.gate_weight = self.create_parameter(
            (d_model, num_experts), default_initializer=I.XavierNormal())
        self.w1 = self.create_parameter(
            (num_experts, d_model, ffn1_out),
            default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter((num_experts, ffn1_out), is_bias=True)
        self.w2 = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter((num_experts, d_model), is_bias=True)

        axis = expert_axis
        if axis is None and mesh_state.has_mesh():
            for cand in ("dp", "mp"):
                if (mesh_state.mesh_axis_size(cand) > 1
                        and num_experts % mesh_state.mesh_axis_size(cand) == 0):
                    axis = cand
                    break
        self.expert_axis = axis
        if axis is not None:
            for p in (self.w1, self.b1, self.w2, self.b2):
                p.is_distributed = True
                spec = [axis] + [None] * (p._value.ndim - 1)
                p._value = mesh_state.shard_value(p._value, *spec)
        if dispatch_mode not in ("auto", "einsum", "grouped"):
            raise ValueError(
                f"dispatch_mode must be auto|einsum|grouped, got "
                f"{dispatch_mode!r}")
        # grouped (sort + lax.ragged_dot) is the perf tier: O(T*k) rows
        # of matmul instead of the dense (T, E, C) einsums. With
        # expert_axis set it runs the shard_map EP schedule (global gate
        # + per-shard ragged_dot, see _grouped_ep_fn); einsum remains the
        # GSPMD fallback for custom gates / non-divisible shapes.
        if dispatch_mode == "auto":
            # custom gate objects only promise the __call__ → (dispatch,
            # combine, cap) contract; grouped needs the sparse
            # topk_assignments form
            dispatch_mode = (
                "grouped" if hasattr(self.gate, "topk_assignments")
                and (axis is None
                     or num_experts % mesh_state.mesh_axis_size(axis) == 0)
                else "einsum")
        if (dispatch_mode == "grouped" and axis is not None
                and num_experts % max(
                    mesh_state.mesh_axis_size(axis), 1) != 0):
            raise ValueError(
                f"grouped EP dispatch needs num_experts ({num_experts}) "
                f"divisible by the {axis!r} axis size "
                f"({mesh_state.mesh_axis_size(axis)})")
        self.dispatch_mode = dispatch_mode

    def _act(self, h):
        if self.activation == "swiglu":
            g_, u_ = jnp.split(h, 2, axis=-1)
            return jax.nn.silu(g_.astype(jnp.float32)).astype(u_.dtype) * u_
        return jax.nn.gelu(h.astype(jnp.float32)).astype(h.dtype)

    def _grouped_fn(self, xv, gw, w1, b1, w2, b2):
        """Sort/segment grouped-matmul dispatch (megablocks-style): the
        T*k routed rows are sorted by expert and fed to
        ``jax.lax.ragged_dot`` with per-expert group sizes — O(T*k)
        matmul rows and O(T*k*M) memory, vs the dense einsum tier's
        (T, E, C) dispatch tensor. Same gate, same capacity-drop
        semantics (dropped rows keep their slot but combine with weight
        zero), same aux loss."""
        cfg = self
        lead = xv.shape[:-1]
        t = 1
        for s in lead:
            t *= s
        k = cfg.gate.top_k
        e = cfg.num_experts
        xt = xv.reshape(t, cfg.d_model)
        logits = xt.astype(jnp.float32) @ gw.astype(jnp.float32)
        topi, gate_vals, aux = cfg.gate.topk_assignments(logits)

        expert_flat = topi.reshape(-1)                    # (T*k,)
        gv_flat = gate_vals.reshape(-1)
        tok_flat = jnp.repeat(jnp.arange(t, dtype=jnp.int32), k)
        order = jnp.argsort(expert_flat)                  # stable
        sorted_tok = tok_flat[order]
        sorted_exp = expert_flat[order]
        sorted_gv = gv_flat[order].astype(xv.dtype)
        group_sizes = jnp.bincount(expert_flat, length=e).astype(jnp.int32)

        xs = xt[sorted_tok]                               # (T*k, M)
        h = jax.lax.ragged_dot(xs, w1.astype(xv.dtype), group_sizes)
        h = h + b1[sorted_exp].astype(xv.dtype)
        h = self._act(h)
        out = jax.lax.ragged_dot(h, w2.astype(xv.dtype), group_sizes)
        out = out + b2[sorted_exp].astype(xv.dtype)
        y = jnp.zeros((t, cfg.d_model), xv.dtype).at[sorted_tok].add(
            out * sorted_gv[:, None])
        return y.reshape(*lead, cfg.d_model), aux

    def _grouped_ep_fn(self, xv, gw, w1, b1, w2, b2):
        """Expert-parallel grouped dispatch: a ``shard_map`` schedule over
        ``expert_axis`` with the same gate/capacity semantics as serial.

        Per device: (1) all-gather the token shard and run the GATE
        GLOBALLY (capacity queueing depends on global token order — a
        per-shard gate would diverge from the serial oracle); (2) sort
        the kept routed rows by expert (identical order on every device)
        and take this shard's expert segment via a dynamic slice whose
        STATIC size is the gate-capacity bound ``(E/P) * cap`` — the gate
        guarantees kept rows per expert ≤ cap, so the slice never
        truncates; (3) ``lax.ragged_dot`` with the local expert weights;
        (4) scatter-add into a (T, M) partial and ``psum_scatter`` back
        to the token owners. Per-device matmul rows scale as T*k*cf/P —
        the EP compute win the dense (T, E, C) einsum tier lacks at long
        T (its cost ∝ T², BENCH_NOTES MoE table). Wire is one all-gather
        + one reduce-scatter of (T, M); swapping the gather/scatter pair
        for ``lax.ragged_all_to_all`` (row exchange ∝ routed tokens) is
        the upgrade path once XLA:CPU implements the op — today it would
        make every CPU-mesh test and the driver dryrun unrunnable."""
        from .gate import _capacity

        cfg = self
        mesh = mesh_state.get_mesh()
        ax = cfg.expert_axis
        pn = int(mesh.shape[ax])
        e = cfg.num_experts
        epp = e // pn
        lead = xv.shape[:-1]
        t = 1
        for s in lead:
            t *= s
        k = cfg.gate.top_k
        cap = _capacity(t, e, cfg.gate.capacity_factor, k)
        slice_rows = min(epp * cap, t * k)
        from .....distributed.fleet.meta_parallel.context_parallel import (
            shard_map,
        )
        from jax.sharding import PartitionSpec as P

        def body(xt_loc, gw_, w1_, b1_, w2_, b2_):
            p = jax.lax.axis_index(ax)
            xt_all = jax.lax.all_gather(xt_loc, ax, axis=0, tiled=True)
            logits = xt_all.astype(jnp.float32) @ gw_.astype(jnp.float32)
            topi, gate_vals, aux = cfg.gate.topk_assignments(logits)
            expert_flat = topi.reshape(-1)
            gv_flat = gate_vals.reshape(-1).astype(xt_all.dtype)
            tok_flat = jnp.repeat(jnp.arange(t, dtype=jnp.int32), k)
            kept = gv_flat > 0
            # dropped rows sort to the sentinel tail: the slice bound
            # below holds for KEPT rows only
            key = jnp.where(kept, expert_flat, e).astype(jnp.int32)
            order = jnp.argsort(key)
            pad_tail = jnp.full((slice_rows,), e, jnp.int32)
            sorted_tok = jnp.concatenate(
                [tok_flat[order], jnp.zeros((slice_rows,), jnp.int32)])
            sorted_exp = jnp.concatenate([key[order], pad_tail])
            sorted_gv = jnp.concatenate(
                [gv_flat[order], jnp.zeros((slice_rows,), gv_flat.dtype)])
            kept_counts = jnp.bincount(key, length=e + 1)[:e]
            start = jnp.sum(
                jnp.where(jnp.arange(e) < p * epp, kept_counts, 0)
            ).astype(jnp.int32)
            rows_tok = jax.lax.dynamic_slice(sorted_tok, (start,),
                                             (slice_rows,))
            rows_exp = jax.lax.dynamic_slice(sorted_exp, (start,),
                                             (slice_rows,))
            rows_gv = jax.lax.dynamic_slice(sorted_gv, (start,),
                                            (slice_rows,))
            xs = xt_all[rows_tok]
            mine = (rows_exp >= p * epp) & (rows_exp < (p + 1) * epp)
            local_exp = jnp.clip(rows_exp - p * epp, 0, epp - 1)
            gs = jax.lax.dynamic_slice(
                kept_counts, (p * epp,), (epp,)).astype(jnp.int32)
            # trailing non-mine rows feed the last group; masked below
            gs = gs.at[-1].add(slice_rows - jnp.sum(gs))
            h = jax.lax.ragged_dot(xs, w1_.astype(xs.dtype), gs)
            h = h + b1_[local_exp].astype(xs.dtype)
            h = cfg._act(h)
            out = jax.lax.ragged_dot(h, w2_.astype(xs.dtype), gs)
            out = out + b2_[local_exp].astype(xs.dtype)
            weight = jnp.where(mine, rows_gv, 0.0)
            y = jnp.zeros((t, cfg.d_model), xs.dtype).at[rows_tok].add(
                out * weight[:, None])
            y_loc = jax.lax.psum_scatter(y, ax, scatter_dimension=0,
                                         tiled=True)
            return y_loc, jax.lax.pmean(aux, ax)

        xt = xv.reshape(t, cfg.d_model)
        y, aux = shard_map(
            body, mesh=mesh,
            in_specs=(P(ax), P(), P(ax), P(ax), P(ax), P(ax)),
            out_specs=(P(ax), P()),
        )(xt, gw, w1, b1, w2, b2)
        return y.reshape(*lead, cfg.d_model), aux

    def forward(self, x):
        """x: (..., d_model) → same shape; self.l_aux holds the aux loss."""
        x = ensure_tensor(x)
        gate = self.gate
        cfg = self

        if self.dispatch_mode == "grouped":
            ep = self.expert_axis is not None and mesh_state.has_mesh() \
                and mesh_state.mesh_axis_size(self.expert_axis) > 1
            if ep:
                t = 1
                for s in x.shape[:-1]:
                    t *= s
                pn = mesh_state.mesh_axis_size(self.expert_axis)
                # the mesh may be installed AFTER construction, so the
                # num_experts divisibility must be re-checked here too —
                # inside shard_map it would fail as an opaque in_specs
                # error on the expert weights
                if t % pn != 0 or self.num_experts % pn != 0:
                    import warnings

                    warnings.warn(
                        f"grouped EP dispatch needs token count {t} and "
                        f"num_experts {self.num_experts} divisible by "
                        f"{self.expert_axis}={pn}; falling back to the "
                        f"einsum tier", RuntimeWarning)
                else:
                    out, self.l_aux = apply(
                        self._grouped_ep_fn, x, self.gate_weight, self.w1,
                        self.b1, self.w2, self.b2,
                        op_name="moe_layer_grouped_ep")
                    return out
            else:
                out, self.l_aux = apply(
                    self._grouped_fn, x, self.gate_weight, self.w1, self.b1,
                    self.w2, self.b2, op_name="moe_layer_grouped")
                return out

        def fn(xv, gw, w1, b1, w2, b2):
            lead = xv.shape[:-1]
            t = 1
            for s in lead:
                t *= s
            xt = xv.reshape(t, cfg.d_model)
            logits = xt.astype(jnp.float32) @ gw.astype(jnp.float32)
            dispatch, combine, cap = gate(logits)
            aux = gate.l_aux
            # dispatch: (T, E, C) → expert inputs (E, C, M)
            disp = jnp.einsum(
                "tec,tm->ecm", dispatch.astype(xv.dtype), xt)
            if cfg.expert_axis is not None:
                disp = mesh_state.constraint(disp, cfg.expert_axis, None, None)
            h = jnp.einsum("ecm,emh->ech", disp, w1.astype(xv.dtype))
            h = h + b1[:, None, :].astype(xv.dtype)
            h = cfg._act(h)
            out = jnp.einsum("ech,ehm->ecm", h, w2.astype(xv.dtype))
            out = out + b2[:, None, :].astype(xv.dtype)
            if cfg.expert_axis is not None:
                out = mesh_state.constraint(out, cfg.expert_axis, None, None)
            y = jnp.einsum("tec,ecm->tm", combine.astype(xv.dtype), out)
            # aux returned through the op so the load-balancing loss stays
            # on the tape (differentiable into gate_weight)
            return y.reshape(*lead, cfg.d_model), aux

        out, self.l_aux = apply(
            fn, x, self.gate_weight, self.w1, self.b1, self.w2,
            self.b2, op_name="moe_layer")
        return out
