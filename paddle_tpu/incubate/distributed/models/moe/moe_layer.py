"""MoELayer — expert-parallel FFN mixture (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py — unverified,
SURVEY.md §0/§2.3 EP row).

Experts are a stacked SwiGLU/GELU FFN: weights (num_experts, ...) sharded
over an ``expert`` mesh axis. Dispatch/combine are the GShard einsums —
under a mesh, constraining the dispatched tensor's expert dim makes GSPMD
emit the all-to-all over ICI (the reference's GlobalScatter/GlobalGather
NCCL ops)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer.layers import Layer
from .....nn import initializer as I
from .....tensor._helpers import apply, ensure_tensor
from .....parallel import mesh as mesh_state
from .gate import TopKGate, SwitchGate

__all__ = ["MoELayer"]


class MoELayer(Layer):
    """MoE FFN block.

    Args:
        d_model: token dim.
        d_hidden: expert FFN hidden dim.
        num_experts: global expert count.
        gate: "gshard" | "switch" | a gate object (default top-2).
        activation: "gelu" | "swiglu".
        expert_axis: mesh axis experts shard over (default: "dp" when its
            size divides num_experts, else "mp"; no mesh → serial).
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 activation="gelu", capacity_factor=2.0, expert_axis=None,
                 dispatch_mode="auto", name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.activation = activation
        if isinstance(gate, str):
            gate = {"gshard": TopKGate(2, capacity_factor),
                    "switch": SwitchGate(capacity_factor),
                    "top2": TopKGate(2, capacity_factor)}[gate]
        self.gate = gate
        self.l_aux = None

        ffn1_out = 2 * d_hidden if activation == "swiglu" else d_hidden
        self.gate_weight = self.create_parameter(
            (d_model, num_experts), default_initializer=I.XavierNormal())
        self.w1 = self.create_parameter(
            (num_experts, d_model, ffn1_out),
            default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter((num_experts, ffn1_out), is_bias=True)
        self.w2 = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter((num_experts, d_model), is_bias=True)

        axis = expert_axis
        if axis is None and mesh_state.has_mesh():
            for cand in ("dp", "mp"):
                if (mesh_state.mesh_axis_size(cand) > 1
                        and num_experts % mesh_state.mesh_axis_size(cand) == 0):
                    axis = cand
                    break
        self.expert_axis = axis
        if axis is not None:
            for p in (self.w1, self.b1, self.w2, self.b2):
                p.is_distributed = True
                spec = [axis] + [None] * (p._value.ndim - 1)
                p._value = mesh_state.shard_value(p._value, *spec)
        if dispatch_mode not in ("auto", "einsum", "grouped"):
            raise ValueError(
                f"dispatch_mode must be auto|einsum|grouped, got "
                f"{dispatch_mode!r}")
        # grouped (sort + lax.ragged_dot) is the perf tier: O(T*k) rows
        # of matmul instead of the dense (T, E, C) einsums. The einsum
        # tier remains the EP-sharded path — GSPMD turns its expert-dim
        # constraints into the all-to-all; the sorted ragged layout has
        # no static per-device partition for the partitioner to use.
        if dispatch_mode == "auto":
            # custom gate objects only promise the __call__ → (dispatch,
            # combine, cap) contract; grouped needs the sparse
            # topk_assignments form
            dispatch_mode = (
                "grouped" if axis is None
                and hasattr(self.gate, "topk_assignments") else "einsum")
        if dispatch_mode == "grouped" and axis is not None:
            raise ValueError(
                "dispatch_mode='grouped' is the single-device/local tier;"
                " EP-sharded experts use the einsum path (GSPMD"
                " all-to-all)"
            )
        self.dispatch_mode = dispatch_mode

    def _act(self, h):
        if self.activation == "swiglu":
            g_, u_ = jnp.split(h, 2, axis=-1)
            return jax.nn.silu(g_.astype(jnp.float32)).astype(u_.dtype) * u_
        return jax.nn.gelu(h.astype(jnp.float32)).astype(h.dtype)

    def _grouped_fn(self, xv, gw, w1, b1, w2, b2):
        """Sort/segment grouped-matmul dispatch (megablocks-style): the
        T*k routed rows are sorted by expert and fed to
        ``jax.lax.ragged_dot`` with per-expert group sizes — O(T*k)
        matmul rows and O(T*k*M) memory, vs the dense einsum tier's
        (T, E, C) dispatch tensor. Same gate, same capacity-drop
        semantics (dropped rows keep their slot but combine with weight
        zero), same aux loss."""
        cfg = self
        lead = xv.shape[:-1]
        t = 1
        for s in lead:
            t *= s
        k = cfg.gate.top_k
        e = cfg.num_experts
        xt = xv.reshape(t, cfg.d_model)
        logits = xt.astype(jnp.float32) @ gw.astype(jnp.float32)
        topi, gate_vals, aux = cfg.gate.topk_assignments(logits)

        expert_flat = topi.reshape(-1)                    # (T*k,)
        gv_flat = gate_vals.reshape(-1)
        tok_flat = jnp.repeat(jnp.arange(t, dtype=jnp.int32), k)
        order = jnp.argsort(expert_flat)                  # stable
        sorted_tok = tok_flat[order]
        sorted_exp = expert_flat[order]
        sorted_gv = gv_flat[order].astype(xv.dtype)
        group_sizes = jnp.bincount(expert_flat, length=e).astype(jnp.int32)

        xs = xt[sorted_tok]                               # (T*k, M)
        h = jax.lax.ragged_dot(xs, w1.astype(xv.dtype), group_sizes)
        h = h + b1[sorted_exp].astype(xv.dtype)
        h = self._act(h)
        out = jax.lax.ragged_dot(h, w2.astype(xv.dtype), group_sizes)
        out = out + b2[sorted_exp].astype(xv.dtype)
        y = jnp.zeros((t, cfg.d_model), xv.dtype).at[sorted_tok].add(
            out * sorted_gv[:, None])
        return y.reshape(*lead, cfg.d_model), aux

    def forward(self, x):
        """x: (..., d_model) → same shape; self.l_aux holds the aux loss."""
        x = ensure_tensor(x)
        gate = self.gate
        cfg = self

        if self.dispatch_mode == "grouped":
            out, self.l_aux = apply(
                self._grouped_fn, x, self.gate_weight, self.w1, self.b1,
                self.w2, self.b2, op_name="moe_layer_grouped")
            return out

        def fn(xv, gw, w1, b1, w2, b2):
            lead = xv.shape[:-1]
            t = 1
            for s in lead:
                t *= s
            xt = xv.reshape(t, cfg.d_model)
            logits = xt.astype(jnp.float32) @ gw.astype(jnp.float32)
            dispatch, combine, cap = gate(logits)
            aux = gate.l_aux
            # dispatch: (T, E, C) → expert inputs (E, C, M)
            disp = jnp.einsum(
                "tec,tm->ecm", dispatch.astype(xv.dtype), xt)
            if cfg.expert_axis is not None:
                disp = mesh_state.constraint(disp, cfg.expert_axis, None, None)
            h = jnp.einsum("ecm,emh->ech", disp, w1.astype(xv.dtype))
            h = h + b1[:, None, :].astype(xv.dtype)
            h = cfg._act(h)
            out = jnp.einsum("ech,ehm->ecm", h, w2.astype(xv.dtype))
            out = out + b2[:, None, :].astype(xv.dtype)
            if cfg.expert_axis is not None:
                out = mesh_state.constraint(out, cfg.expert_axis, None, None)
            y = jnp.einsum("tec,ecm->tm", combine.astype(xv.dtype), out)
            # aux returned through the op so the load-balancing loss stays
            # on the tape (differentiable into gate_weight)
            return y.reshape(*lead, cfg.d_model), aux

        out, self.l_aux = apply(
            fn, x, self.gate_weight, self.w1, self.b1, self.w2,
            self.b2, op_name="moe_layer")
        return out
