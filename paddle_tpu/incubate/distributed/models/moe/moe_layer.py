"""MoELayer — expert-parallel FFN mixture (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py — unverified,
SURVEY.md §0/§2.3 EP row).

Experts are a stacked SwiGLU/GELU FFN: weights (num_experts, ...) sharded
over an ``expert`` mesh axis. Dispatch/combine are the GShard einsums —
under a mesh, constraining the dispatched tensor's expert dim makes GSPMD
emit the all-to-all over ICI (the reference's GlobalScatter/GlobalGather
NCCL ops)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer.layers import Layer
from .....nn import initializer as I
from .....tensor._helpers import apply, ensure_tensor
from .....parallel import mesh as mesh_state
from .gate import TopKGate, SwitchGate

__all__ = ["MoELayer"]


class MoELayer(Layer):
    """MoE FFN block.

    Args:
        d_model: token dim.
        d_hidden: expert FFN hidden dim.
        num_experts: global expert count.
        gate: "gshard" | "switch" | a gate object (default top-2).
        activation: "gelu" | "swiglu".
        expert_axis: mesh axis experts shard over (default: "dp" when its
            size divides num_experts, else "mp"; no mesh → serial).
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 activation="gelu", capacity_factor=2.0, expert_axis=None,
                 name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.activation = activation
        if isinstance(gate, str):
            gate = {"gshard": TopKGate(2, capacity_factor),
                    "switch": SwitchGate(capacity_factor),
                    "top2": TopKGate(2, capacity_factor)}[gate]
        self.gate = gate
        self.l_aux = None

        ffn1_out = 2 * d_hidden if activation == "swiglu" else d_hidden
        self.gate_weight = self.create_parameter(
            (d_model, num_experts), default_initializer=I.XavierNormal())
        self.w1 = self.create_parameter(
            (num_experts, d_model, ffn1_out),
            default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter((num_experts, ffn1_out), is_bias=True)
        self.w2 = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter((num_experts, d_model), is_bias=True)

        axis = expert_axis
        if axis is None and mesh_state.has_mesh():
            for cand in ("dp", "mp"):
                if (mesh_state.mesh_axis_size(cand) > 1
                        and num_experts % mesh_state.mesh_axis_size(cand) == 0):
                    axis = cand
                    break
        self.expert_axis = axis
        if axis is not None:
            for p in (self.w1, self.b1, self.w2, self.b2):
                p.is_distributed = True
                spec = [axis] + [None] * (p._value.ndim - 1)
                p._value = mesh_state.shard_value(p._value, *spec)

    def forward(self, x):
        """x: (..., d_model) → same shape; self.l_aux holds the aux loss."""
        x = ensure_tensor(x)
        gate = self.gate
        cfg = self

        def fn(xv, gw, w1, b1, w2, b2):
            lead = xv.shape[:-1]
            t = 1
            for s in lead:
                t *= s
            xt = xv.reshape(t, cfg.d_model)
            logits = xt.astype(jnp.float32) @ gw.astype(jnp.float32)
            dispatch, combine, cap = gate(logits)
            aux = gate.l_aux
            # dispatch: (T, E, C) → expert inputs (E, C, M)
            disp = jnp.einsum(
                "tec,tm->ecm", dispatch.astype(xv.dtype), xt)
            if cfg.expert_axis is not None:
                disp = mesh_state.constraint(disp, cfg.expert_axis, None, None)
            h = jnp.einsum("ecm,emh->ech", disp, w1.astype(xv.dtype))
            h = h + b1[:, None, :].astype(xv.dtype)
            if cfg.activation == "swiglu":
                g_, u_ = jnp.split(h, 2, axis=-1)
                h = jax.nn.silu(g_.astype(jnp.float32)).astype(u_.dtype) * u_
            else:
                h = jax.nn.gelu(h.astype(jnp.float32)).astype(h.dtype)
            out = jnp.einsum("ech,ehm->ecm", h, w2.astype(xv.dtype))
            out = out + b2[:, None, :].astype(xv.dtype)
            if cfg.expert_axis is not None:
                out = mesh_state.constraint(out, cfg.expert_axis, None, None)
            y = jnp.einsum("tec,ecm->tm", combine.astype(xv.dtype), out)
            # aux returned through the op so the load-balancing loss stays
            # on the tape (differentiable into gate_weight)
            return y.reshape(*lead, cfg.d_model), aux

        out, self.l_aux = apply(
            fn, x, self.gate_weight, self.w1, self.b1, self.w2,
            self.b2, op_name="moe_layer")
        return out
