"""MoE gates (reference: .../moe/gate/{gshard,switch,naive}_gate.py —
unverified, SURVEY.md §0).

A gate maps token activations (T, E_model) → routing decisions. The
capacity-based formulation returns dense one-hot dispatch/combine masks
(T, num_experts, capacity) that downstream einsums consume; the
load-balancing auxiliary loss (GShard eq. 4) is stored on the gate.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["TopKGate", "GShardGate", "SwitchGate"]


def _capacity(num_tokens, num_experts, capacity_factor, top_k):
    cap = int(num_tokens * top_k * capacity_factor / num_experts)
    return max(cap, top_k)


def _one_hot_dispatch(gates, top_k, capacity):
    """gates (T, E) softmax probs → (dispatch (T,E,C) bool, combine
    (T,E,C) float, aux_loss scalar)."""
    t, e = gates.shape
    # straight GShard: iterate the k choices, masking prior picks
    dispatch = jnp.zeros((t, e, capacity), jnp.bool_)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    masked = gates
    me = jnp.mean(gates, axis=0)          # mean prob per expert
    ce_counts = jnp.zeros((e,), jnp.float32)
    # position counters per expert, threaded across the k rounds
    pos_base = jnp.zeros((e,), jnp.int32)
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=1)                       # (T,)
        sel = jax.nn.one_hot(idx, e, dtype=jnp.float32)        # (T, E)
        ce_counts = ce_counts + jnp.sum(sel, axis=0)
        # position of each token within its expert's queue this round
        pos_in = jnp.cumsum(sel, axis=0) - sel                 # (T, E)
        pos = (pos_in + pos_base[None, :]).astype(jnp.int32)
        within = pos < capacity
        keep = (sel > 0) & within                              # (T, E)
        posc = jax.nn.one_hot(
            jnp.sum(pos * sel.astype(jnp.int32), axis=1), capacity,
            dtype=jnp.float32)                                 # (T, C)
        disp_k = keep[:, :, None] & (posc[:, None, :] > 0)
        dispatch = dispatch | disp_k
        gate_val = jnp.sum(gates * sel, axis=1)                # (T,)
        combine = combine + disp_k.astype(jnp.float32) * gate_val[:, None, None]
        pos_base = pos_base + jnp.sum(keep, axis=0).astype(jnp.int32)
        masked = jnp.where(sel > 0, -jnp.inf, masked)
    # GShard aux loss: E * mean(fraction_routed * mean_prob)
    fraction = ce_counts / jnp.maximum(jnp.sum(ce_counts), 1.0)
    aux = jnp.sum(fraction * me) * e
    return dispatch, combine, aux


class TopKGate:
    """Dense top-k capacity gate over a learned projection."""

    def __init__(self, top_k=2, capacity_factor=1.25):
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.l_aux = None

    def __call__(self, logits):
        """logits (T, E) → (dispatch, combine, capacity)."""
        t, e = logits.shape
        cap = _capacity(t, e, self.capacity_factor, self.top_k)
        gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        dispatch, combine, aux = _one_hot_dispatch(gates, self.top_k, cap)
        self.l_aux = aux
        return dispatch, combine, cap

    def topk_assignments(self, logits):
        """Sparse form of the SAME routing decision (grouped-matmul
        dispatch tier): logits (T, E) → (expert_ids (T, k), gate_vals
        (T, k) with capacity-dropped slots zeroed, aux). Capacity
        semantics match __call__: round-major queueing — every token's
        r-th choice is queued before any token's (r+1)-th choice."""
        t, e = logits.shape
        cap = _capacity(t, e, self.capacity_factor, self.top_k)
        gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        topv, topi = jax.lax.top_k(gates, self.top_k)   # (T, k) desc
        ce_counts = jnp.zeros((e,), jnp.float32)
        pos_base = jnp.zeros((e,), jnp.int32)
        kept = []
        for r in range(self.top_k):
            sel = jax.nn.one_hot(topi[:, r], e, dtype=jnp.float32)
            ce_counts = ce_counts + jnp.sum(sel, axis=0)
            pos_in = jnp.cumsum(sel, axis=0) - sel
            pos = (pos_in + pos_base[None, :]).astype(jnp.int32)
            keep = (sel > 0) & (pos < cap)              # (T, E)
            pos_base = pos_base + jnp.sum(keep, axis=0).astype(jnp.int32)
            kept.append(jnp.any(keep, axis=1))
        keep_mask = jnp.stack(kept, axis=1)             # (T, k)
        gate_vals = topv * keep_mask.astype(topv.dtype)
        me = jnp.mean(gates, axis=0)
        fraction = ce_counts / jnp.maximum(jnp.sum(ce_counts), 1.0)
        aux = jnp.sum(fraction * me) * e
        self.l_aux = aux
        return topi, gate_vals, aux


class GShardGate(TopKGate):
    def __init__(self, capacity_factor=2.0):
        super().__init__(top_k=2, capacity_factor=capacity_factor)


class SwitchGate(TopKGate):
    def __init__(self, capacity_factor=1.25):
        super().__init__(top_k=1, capacity_factor=capacity_factor)
