"""FusedMultiTransformer — the whole decoder stack as one op.

Reference shape: paddle.incubate.nn.FusedMultiTransformer
(fused_multi_transformer_op.cu): per layer
{pre-LN → qkv → rotary → cached MHA → out-proj → LN → FFN}, incremental
decode against a KV cache. TPU-native mechanics: stacked (L, ...) weights
scanned with ``lax.scan``; prefill uses the Pallas flash kernel, decode
the Pallas KV-cache kernel; TP sharding via mp-axis NamedShardings on the
stacked weights (GSPMD inserts the reference's mp_allreduce).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...nn.layer.layers import Layer
from ...nn import functional as F
from ...nn.functional.rope import build_rope_cache, apply_rotary_emb
from ...nn import initializer as I
from ...tensor._helpers import Tensor, apply, ensure_tensor
from ...parallel import mesh as mesh_state

__all__ = ["FusedMultiTransformer"]


class FusedMultiTransformer(Layer):
    """Pre-LN decoder stack with KV-cache decode.

    Args mirror the reference; weights are held STACKED with a leading
    ``num_layers`` dim (state_dict keys expose per-layer views on save).
    norm_type: "layernorm" | "rmsnorm"; activation: "gelu" | "swiglu".
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, num_layers=1,
                 nranks=1, trans_qkvw=True, ring_id=-1,
                 norm_type="layernorm", use_neox_rotary_style=True,
                 num_key_value_heads=None, epsilon=1e-5,
                 rope_theta=10000.0, name=None):
        super().__init__()
        assert normalize_before, "FusedMultiTransformer is pre-LN"
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.num_kv_heads = num_key_value_heads or num_heads
        self.head_dim = embed_dim // num_heads
        self.dim_feedforward = dim_feedforward
        self.num_layers = num_layers
        self.activation = activation
        self.norm_type = norm_type
        self.use_neox = use_neox_rotary_style
        self.epsilon = epsilon
        self.rope_theta = rope_theta
        L, E, H, HK, D, FFN = (num_layers, embed_dim, num_heads,
                               self.num_kv_heads, self.head_dim,
                               dim_feedforward)
        qkv_out = (H + 2 * HK) * D
        ffn1_out = 2 * FFN if activation == "swiglu" else FFN

        def mk(shape, is_bias=False, shard=None):
            p = self.create_parameter(
                shape, is_bias=is_bias,
                default_initializer=I.Constant(0.0) if is_bias
                else I.XavierNormal(),
            )
            if shard is not None and mesh_state.has_mesh():
                p.is_distributed = True
                p._value = mesh_state.shard_value(p._value, *shard)
            return p

        # stacked weights; mp-sharded like Column/RowParallelLinear
        self.ln_scale = mk((L, E))
        self.ln_bias = mk((L, E), is_bias=True) if norm_type == "layernorm" else None
        self.qkv_weight = mk((L, E, qkv_out), shard=(None, None, "mp"))
        self.qkv_bias = mk((L, qkv_out), is_bias=True, shard=(None, "mp"))
        self.linear_weight = mk((L, H * D, E), shard=(None, "mp", None))
        self.linear_bias = mk((L, E), is_bias=True)
        self.ffn_ln_scale = mk((L, E))
        self.ffn_ln_bias = mk((L, E), is_bias=True) if norm_type == "layernorm" else None
        self.ffn1_weight = mk((L, E, ffn1_out), shard=(None, None, "mp"))
        self.ffn1_bias = mk((L, ffn1_out), is_bias=True, shard=(None, "mp"))
        self.ffn2_weight = mk((L, FFN, E), shard=(None, "mp", None))
        self.ffn2_bias = mk((L, E), is_bias=True)
        # weight-only int8 serving tier (reference:
        # fused_multi_transformer_int8): scales are (L, out) per-channel;
        # None until quantize_weight_only() installs them
        self._wo_int8 = False
        self.qkv_weight_scale = None
        self.linear_weight_scale = None
        self.ffn1_weight_scale = None
        self.ffn2_weight_scale = None

    def quantize_weight_only(self):
        """Convert the four matmul weight stacks to int8 with per-layer,
        per-out-channel scales (paddle.nn.quant.weight_quantize algo) —
        the reference's int8 fused_multi_transformer variant. Weights
        stay int8 in HBM; the scale multiply rides the matmul epilogue.
        Idempotent; returns self."""
        from ...nn.quant import weight_quantize_stacked

        if self._wo_int8:
            return self
        # the int8 weight keeps the float original's mp sharding; its
        # (L, out) scale shards like the out dim
        shards = {
            "qkv_weight": ((None, None, "mp"), (None, "mp")),
            "linear_weight": ((None, "mp", None), (None, None)),
            "ffn1_weight": ((None, None, "mp"), (None, "mp")),
            "ffn2_weight": ((None, "mp", None), (None, None)),
        }
        for name, (w_spec, s_spec) in shards.items():
            w = getattr(self, name)._value  # (L, in, out)
            q, scale = weight_quantize_stacked(w, axis=1)
            qp = self.create_parameter(
                tuple(q.shape), dtype="int8",
                default_initializer=lambda shape, dtype, q=q: q)
            qp.stop_gradient = True
            sp = self.create_parameter(
                tuple(scale.shape), dtype="float32",
                default_initializer=lambda shape, dtype, s=scale: s)
            sp.stop_gradient = True
            if mesh_state.has_mesh():
                qp.is_distributed = True
                qp._value = mesh_state.shard_value(qp._value, *w_spec)
                sp.is_distributed = True
                sp._value = mesh_state.shard_value(sp._value, *s_spec)
            setattr(self, name, qp)
            setattr(self, name + "_scale", sp)
        self._wo_int8 = True
        return self

    def gen_cache(self, batch_size, max_length, dtype="float32"):
        """Stacked KV caches: pair of (L, B, S_max, HK, D) Tensors."""
        import paddle_tpu as paddle

        shape = [self.num_layers, batch_size, max_length,
                 self.num_kv_heads, self.head_dim]
        return paddle.zeros(shape, dtype), paddle.zeros(shape, dtype)

    # -- the fused stack -----------------------------------------------------
    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, seq_lens=None,
                time_step=None, name=None):
        """src: (B, S, E). With ``caches`` (from gen_cache) and
        ``time_step`` (int position offset), runs incremental decode;
        otherwise a causal prefill (writing caches when given).
        Returns out or (out, caches)."""
        src = ensure_tensor(src)
        args = [src]
        have_caches = caches is not None
        if have_caches:
            args += [ensure_tensor(caches[0]), ensure_tensor(caches[1])]
        if seq_lens is not None:
            args.append(ensure_tensor(seq_lens))

        offset = int(time_step) if time_step is not None else 0
        weights = [
            self.ln_scale, self.ln_bias, self.qkv_weight, self.qkv_bias,
            self.linear_weight, self.linear_bias, self.ffn_ln_scale,
            self.ffn_ln_bias, self.ffn1_weight, self.ffn1_bias,
            self.ffn2_weight, self.ffn2_bias,
            # weight-only int8 per-channel scales (None when float)
            self.qkv_weight_scale, self.linear_weight_scale,
            self.ffn1_weight_scale, self.ffn2_weight_scale,
        ]
        w_idx = [i for i, w in enumerate(weights) if w is not None]
        w_tensors = [weights[i] for i in w_idx]

        n_in = len(args)

        def fn(*vals):
            src_v = vals[0]
            kc = vals[1] if have_caches else None
            vc = vals[2] if have_caches else None
            lens_v = vals[n_in - 1] if seq_lens is not None else None
            wt = {i: v for i, v in zip(w_idx, vals[n_in:])}
            out, new_kc, new_vc = _fused_stack(
                src_v, kc, vc, lens_v, wt, self, offset)
            if have_caches:
                return out, new_kc, new_vc
            return out

        result = apply(fn, *args, *w_tensors, op_name="fused_multi_transformer")
        if have_caches:
            out, new_kc, new_vc = result
            return out, (new_kc, new_vc)
        return result


def _norm(x, scale, bias, kind, eps):
    xf = x.astype(jnp.float32)
    if kind == "rmsnorm":
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        out = xf * jax.lax.rsqrt(var + eps)
    else:
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + eps)
    out = out * scale
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)


def _fused_stack(src, kc, vc, lens, wt, cfg: FusedMultiTransformer, offset,
                 decode=None):
    """The scan over layers. src (B,S,E); kc/vc (L,B,Smax,HK,D) or None.
    ``offset`` may be a traced int32 when ``decode`` is passed explicitly
    (the branch choice must be static; everything else — rope positions,
    cache update slice, default lens — traces fine)."""
    b, s, e = src.shape
    H, HK, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    if decode is None:
        decode = kc is not None and s == 1 and offset > 0
    else:
        decode = bool(decode) and kc is not None and s == 1

    cos, sin = build_rope_cache(s, D, base=cfg.rope_theta,
                                position_offset=offset)

    def _mm(xv, w, scale):
        """x @ w with the weight-only-int8 dequant riding the epilogue:
        per-out-channel scale commutes with the contraction, so the int8
        weight feeds the MXU directly and one multiply follows."""
        y = xv @ w.astype(xv.dtype)
        if scale is not None:
            y = y * scale.astype(xv.dtype)
        return y

    def layer_step(hidden, xs):
        (ln_s, ln_b, qkv_w, qkv_b, lin_w, lin_b, fln_s, fln_b,
         f1_w, f1_b, f2_w, f2_b, kci, vci,
         qkv_s, lin_s, f1_s, f2_s) = xs
        residual = hidden
        x = _norm(hidden, ln_s, ln_b, cfg.norm_type, cfg.epsilon)
        qkv = _mm(x, qkv_w, qkv_s) + qkv_b.astype(x.dtype)
        q = qkv[..., : H * D].reshape(b, s, H, D)
        k = qkv[..., H * D : (H + HK) * D].reshape(b, s, HK, D)
        v = qkv[..., (H + HK) * D :].reshape(b, s, HK, D)
        q = apply_rotary_emb(q, cos, sin, neox=cfg.use_neox)
        k = apply_rotary_emb(k, cos, sin, neox=cfg.use_neox)

        new_kci, new_vci = kci, vci
        if kci is not None:
            new_kci = jax.lax.dynamic_update_slice_in_dim(
                kci, k.astype(kci.dtype), offset, axis=1)
            new_vci = jax.lax.dynamic_update_slice_in_dim(
                vci, v.astype(vci.dtype), offset, axis=1)

        if decode:
            if lens is not None:
                lens_v = lens.astype(jnp.int32)
            else:
                lens_v = jnp.full((b,), offset + s, jnp.int32)
            if jax.default_backend() == "tpu":
                from ...ops.pallas.decode_attention import decode_attention

                attn = decode_attention(q[:, 0], new_kci, new_vci, lens_v)
                attn = attn[:, None]
            else:
                attn = _masked_decode_attn(q, new_kci, new_vci, lens_v)
        else:
            kk = new_kci[:, : offset + s] if kci is not None else k
            vv = new_vci[:, : offset + s] if vci is not None else v
            attn = F.scaled_dot_product_attention(
                Tensor(q), Tensor(kk.astype(q.dtype)),
                Tensor(vv.astype(q.dtype)), is_causal=True)._value
        attn = attn.reshape(b, s, H * D)
        out = _mm(attn, lin_w, lin_s) + lin_b.astype(attn.dtype)
        hidden = residual + out

        residual = hidden
        x = _norm(hidden, fln_s, fln_b, cfg.norm_type, cfg.epsilon)
        h1 = _mm(x, f1_w, f1_s) + f1_b.astype(x.dtype)
        if cfg.activation == "swiglu":
            gate, up = jnp.split(h1, 2, axis=-1)
            h1 = jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up
        else:
            h1 = jax.nn.gelu(h1.astype(jnp.float32)).astype(h1.dtype)
        out = _mm(h1, f2_w, f2_s) + f2_b.astype(h1.dtype)
        hidden = residual + out
        return hidden, (new_kci, new_vci)

    L = cfg.num_layers
    zeros = jnp.zeros((L, 1), src.dtype)  # placeholder for absent biases
    xs = (
        wt[0], wt.get(1, zeros), wt[2], wt[3], wt[4], wt[5],
        wt[6], wt.get(7, zeros), wt[8], wt[9], wt[10], wt[11],
        kc if kc is not None else jnp.zeros((L, 1), src.dtype),
        vc if vc is not None else jnp.zeros((L, 1), src.dtype),
        wt.get(12, zeros), wt.get(13, zeros),
        wt.get(14, zeros), wt.get(15, zeros),
    )

    def body(hidden, per_layer):
        (ln_s, ln_b, qkv_w, qkv_b, lin_w, lin_b, fln_s, fln_b,
         f1_w, f1_b, f2_w, f2_b, kci, vci,
         qkv_s, lin_s, f1_s, f2_s) = per_layer
        ln_b_ = ln_b if cfg.ln_bias is not None else None
        fln_b_ = fln_b if cfg.ffn_ln_bias is not None else None
        kci_ = kci if kc is not None else None
        vci_ = vci if vc is not None else None
        wo = cfg._wo_int8
        hidden, (nk, nv) = layer_step(
            hidden,
            (ln_s, ln_b_, qkv_w, qkv_b, lin_w, lin_b, fln_s, fln_b_,
             f1_w, f1_b, f2_w, f2_b, kci_, vci_,
             qkv_s if wo else None, lin_s if wo else None,
             f1_s if wo else None, f2_s if wo else None))
        return hidden, (nk if nk is not None else kci,
                        nv if nv is not None else vci)

    hidden, (new_kc, new_vc) = jax.lax.scan(body, src, xs)
    return hidden, new_kc, new_vc


def _masked_decode_attn(q, kc, vc, lens, bias=None):
    """CPU/interpret decode path: masked attention over the cache prefix.
    ``bias``: optional additive logits bias broadcastable to
    (B, H, Sq, S_max)."""
    b, s, h, d = q.shape
    hk = kc.shape[2]
    rep = h // hk
    kr = jnp.repeat(kc, rep, axis=2) if rep > 1 else kc
    vr = jnp.repeat(vc, rep, axis=2) if rep > 1 else vc
    sc = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        kr.astype(jnp.float32)) * sc
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    mask = jnp.arange(kr.shape[1])[None, :] < lens[:, None]
    logits = jnp.where(mask[:, None, None, :], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vr.astype(jnp.float32))
    return out.astype(q.dtype)
