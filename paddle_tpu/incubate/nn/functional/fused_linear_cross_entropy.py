"""Chunked fused lm-head + softmax cross-entropy (reference: PaddleNLP's
fused head-and-loss path used by large-vocab causal-LM training —
unverified, SURVEY.md §0).

At pretrain shapes the unfused loss path materializes the full
``(B*S, V)`` logits THREE times over — bf16 forward logits, the f32
log-softmax, and the f32 logits gradient (≈2.6 GB at B2/S4096/V32k) —
which is exactly the HBM-pressure regime where XLA's scheduler starts
serializing (the measured B2 MFU cliff, BENCH_NOTES round 4).

TPU-native fix: ``lax.scan`` over row chunks computing the loss AND the
(unscaled) gradients in the same pass — cross-entropy's logits gradient
``(softmax - onehot) / count`` does not depend on the upstream cotangent
except through a scalar scale, so the forward contracts each chunk's
gradient to ``dh`` (hidden-sized, bf16) and a running ``dW`` (f32) and
the custom-vjp backward just scales them. Matmul count is identical to
the unfused path (logits, dh, dW — no recompute); peak logits residency
drops from ``N*V`` to ``chunk_rows*V``.

Trade-offs: loss-only (no-grad) callers pay the two gradient matmuls,
and double backward through this op is unsupported (custom_vjp) — it is
a training criterion, not a general layer.
"""
from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ....tensor._helpers import apply, ensure_tensor

__all__ = ["fused_linear_cross_entropy"]


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _lce_core(hs, ys, w, bias, ignore_index):
    loss, _ = _lce_fwd_impl(hs, ys, w, bias, ignore_index)
    return loss


def _lce_fwd_impl(hs, ys, w, bias, ignore_index):
    v = w.shape[1]

    def body(carry, xs):
        s, cnt, dw, db = carry
        h_c, y_c = xs
        logits = jnp.dot(h_c, w, preferred_element_type=jnp.float32)
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)[None, :]
        lse = jax.nn.logsumexp(logits, axis=-1)
        valid = y_c != ignore_index
        safe = jnp.where(valid, y_c, 0).astype(jnp.int32)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        s = s + jnp.sum(jnp.where(valid, lse - picked, 0.0))
        cnt = cnt + jnp.sum(valid.astype(jnp.float32))
        # unscaled logits gradient: softmax - onehot, zero on ignored
        # rows; cast to the activation dtype so the two grad matmuls run
        # on the MXU at the same precision the unfused backward would
        p = jnp.exp(logits - lse[:, None])
        p = p - jax.nn.one_hot(safe, v, dtype=p.dtype)
        p = jnp.where(valid[:, None], p, 0.0).astype(h_c.dtype)
        dh_c = jnp.dot(p, w.T).astype(h_c.dtype)
        dw = dw + jnp.dot(h_c.T, p, preferred_element_type=jnp.float32)
        if bias is not None:
            db = db + jnp.sum(p.astype(jnp.float32), axis=0)
        return (s, cnt, dw, db), dh_c

    dw0 = jnp.zeros(w.shape, jnp.float32)
    db0 = jnp.zeros((v,), jnp.float32) if bias is not None \
        else jnp.float32(0.0)
    (s, cnt, dw, db), dh = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0), dw0, db0), (hs, ys))
    cnt = jnp.maximum(cnt, 1.0)
    return s / cnt, (dh, dw, db, cnt, ys.shape)


def _lce_fwd(hs, ys, w, bias, ignore_index):
    loss, res = _lce_fwd_impl(hs, ys, w, bias, ignore_index)
    # empty dtype-carrier arrays: residual pytrees may hold arrays only
    w_dt = jnp.zeros((0,), w.dtype)
    b_dt = None if bias is None else jnp.zeros((0,), bias.dtype)
    return loss, (res, w_dt, b_dt)


def _lce_bwd(ignore_index, saved, g):
    (dh, dw, db, cnt, y_shape), w_dt, b_dt = saved
    scale = (g / cnt).astype(jnp.float32)
    dy = np.zeros(y_shape, jax.dtypes.float0)  # int labels: no tangent
    dbias = None if b_dt is None else (db * scale).astype(b_dt.dtype)
    return (dh * scale.astype(dh.dtype), dy,
            (dw * scale).astype(w_dt.dtype), dbias)


_lce_core.defvjp(_lce_fwd, _lce_bwd)


def _fused_lce(h, w, y, *maybe_bias, chunk_rows, ignore_index):
    bias = maybe_bias[0] if maybe_bias else None
    hd = h.shape[-1]
    h = h.reshape(-1, hd)
    y = y.reshape(-1)
    n = h.shape[0]
    pad = (-n) % chunk_rows
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        y = jnp.pad(y, (0, pad), constant_values=ignore_index)
    nch = h.shape[0] // chunk_rows
    hs = h.reshape(nch, chunk_rows, hd)
    ys = y.reshape(nch, chunk_rows)
    return _lce_core(hs, ys, w, bias, ignore_index)


def fused_linear_cross_entropy(hidden, weight, labels, bias=None,
                               ignore_index=-100, chunk_rows=1024):
    """Mean softmax cross-entropy of ``hidden @ weight (+ bias)`` against
    ``labels`` without materializing the full logits.

    Args:
        hidden: ``(..., N, H)`` final transformer hidden states (any
            leading batch dims; flattened internally). Typically already
            shifted: ``hidden[:, :-1]`` vs ``labels[:, 1:]``.
        weight: ``(H, V)`` lm-head weight (paddle Linear layout).
        labels: integer class ids broadcastable to ``hidden``'s leading
            dims; positions equal to ``ignore_index`` are excluded from
            both the sum and the mean's denominator.
        bias: optional ``(V,)`` lm-head bias.
        chunk_rows: rows per scan step — peak logits memory is
            ``chunk_rows * V * 4`` bytes.

    Returns the mean loss as a float32 scalar Tensor.
    """
    args = [ensure_tensor(hidden), ensure_tensor(weight),
            ensure_tensor(labels)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply(
        _fused_lce, *args,
        chunk_rows=int(chunk_rows), ignore_index=int(ignore_index),
        op_name="fused_linear_cross_entropy",
    )
