"""paddle.incubate.nn.functional — fused-op functional wrappers.

On TPU most of the reference's CUDA fusions are XLA fusions; the ones
with real kernels here are rope (elementwise, XLA-fused), rms_norm and
flash attention (Pallas). The API shapes mirror the reference wrappers.
"""
from __future__ import annotations

from ....nn.functional.rope import (  # noqa: F401
    fused_rotary_position_embedding,
)
from .fused_linear_cross_entropy import (  # noqa: F401
    fused_linear_cross_entropy,
)
from ....nn import functional as _F
from ....tensor._helpers import ensure_tensor

__all__ = [
    "fused_rotary_position_embedding", "fused_rms_norm", "fused_layer_norm",
    "fused_linear", "fused_bias_act", "fused_multi_head_attention",
    "fused_feedforward", "masked_multihead_attention",
    "fused_linear_cross_entropy",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **quant_kwargs):
    """Reference: fused_bias_residual_layernorm / rms_norm fusion
    (SURVEY.md §2.5). Returns (out, residual_out) like the reference when
    a residual is supplied, else out."""
    if bias is not None:
        x = x + bias
    residual_out = None
    if residual is not None:
        x = x + residual
        residual_out = x
    out = _F.rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis)
    if residual is not None:
        return out, residual_out
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **kw):
    if bias is not None:
        x = x + bias
    residual_out = None
    if residual is not None:
        x = x + residual
        residual_out = x
    shape = ensure_tensor(x).shape[begin_norm_axis:] if begin_norm_axis != -1 \
        else [ensure_tensor(x).shape[-1]]
    out = _F.layer_norm(x, shape, norm_weight, norm_bias, epsilon)
    if residual is not None:
        return out, residual_out
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """cublasLt epilogue analog — XLA fuses dot+bias natively."""
    if transpose_weight:
        weight = ensure_tensor(weight).T
    return _F.linear(x, weight, bias)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    if bias is not None:
        x = x + bias
    act = getattr(_F, act_method)
    return act(x)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, name=None):
    """Training-time fused MHA block (reference: fused_attention_op.cu —
    SURVEY.md §2.5); composed here from flash attention + XLA epilogues."""
    x = ensure_tensor(x)
    b, s, e = x.shape
    residual = x
    if pre_layer_norm:
        x = _F.layer_norm(x, [e], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qkv_w = ensure_tensor(qkv_weight)  # (3, H, D, E) paddle layout
    three, h, d, _ = qkv_w.shape
    qkv = _F.linear(x, qkv_w.reshape([3 * h * d, e]).T,
                    None if qkv_bias is None
                    else ensure_tensor(qkv_bias).reshape([3 * h * d]))
    qkv = qkv.reshape([b, s, 3, h, d])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attn = _F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)
    attn = attn.reshape([b, s, h * d])
    out = _F.linear(attn, linear_weight, linear_bias)
    if dropout_rate:
        out = _F.dropout(out, p=dropout_rate, training=training)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = _F.layer_norm(out, [e], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      ring_id=-1, add_residual=True, name=None):
    """Reference: fused_feedforward_op.cu (SURVEY.md §2.5)."""
    x = ensure_tensor(x)
    e = x.shape[-1]
    residual = x
    if pre_layer_norm:
        x = _F.layer_norm(x, [e], ln1_scale, ln1_bias, ln1_epsilon)
    act = getattr(_F, activation)
    h = act(_F.linear(x, linear1_weight, linear1_bias))
    if dropout1_rate:
        h = _F.dropout(h, p=dropout1_rate, training=training)
    out = _F.linear(h, linear2_weight, linear2_bias)
    if dropout2_rate:
        out = _F.dropout(out, p=dropout2_rate, training=training)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = _F.layer_norm(out, [e], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def masked_multihead_attention(x, cache_kv=None, src_mask=None,
                               sequence_lengths=None, out_scale=-1,
                               num_heads=None, name=None, **kwargs):
    """One-token decode attention over a KV cache (reference:
    paddle.incubate.nn.functional.masked_multihead_attention, the
    fused decode op behind fused_multi_transformer). Routes to the
    Pallas decode kernel on TPU (masked XLA attention elsewhere or when
    ``src_mask`` needs arbitrary biasing).

    x: (B, H, D) or (B, 1, H, D) new-token queries; cache_kv:
    (2, B, S_max, HK, D) stacked k/v caches (paddle layout);
    sequence_lengths: (B,) valid entries incl. the new token;
    src_mask: optional additive bias broadcastable to (B, H, 1, S_max).
    out_scale > 0 quantizes the output to int8 inside the op —
    ``clip(round(out / out_scale), -128, 127)`` (a8w8 serving epilogue;
    reference applies it in the fused CUDA op — unverified, SURVEY §0)."""
    import jax
    import jax.numpy as jnp
    from ....core.flags import get_flags
    from ....tensor._helpers import apply

    if cache_kv is None or sequence_lengths is None:
        raise ValueError(
            "masked_multihead_attention requires cache_kv and "
            "sequence_lengths"
        )
    x = ensure_tensor(x)
    cache_kv = ensure_tensor(cache_kv)
    sequence_lengths = ensure_tensor(sequence_lengths)
    if src_mask is not None:
        src_mask = ensure_tensor(src_mask)

    flags = get_flags(["FLAGS_use_pallas_kernels", "FLAGS_pallas_force"])
    use_pallas = (
        flags["FLAGS_use_pallas_kernels"]
        and (jax.default_backend() == "tpu" or flags["FLAGS_pallas_force"])
        and src_mask is None  # arbitrary bias → XLA path
    )

    def fn(q, ckv, lens, *maybe_mask):
        kc, vc = ckv[0], ckv[1]
        if use_pallas:
            from ....ops.pallas.decode_attention import decode_attention

            out = decode_attention(q, kc, vc, lens.astype(jnp.int32))
        else:
            from ..fused_transformer import _masked_decode_attn as _mda

            q4 = q if q.ndim == 4 else q[:, None]
            out = _mda(q4, kc, vc, lens,
                       bias=maybe_mask[0] if maybe_mask else None)
            out = out if q.ndim == 4 else out[:, 0]
        if out_scale and out_scale > 0:
            out = jnp.clip(
                jnp.round(out.astype(jnp.float32) / float(out_scale)),
                -128, 127).astype(jnp.int8)
        return out

    args = [x, cache_kv, sequence_lengths]
    if src_mask is not None:
        args.append(src_mask)
    return apply(fn, *args, op_name="masked_multihead_attention")


def block_multihead_attention(qkv, key_cache, value_cache,
                              seq_lens_encoder, seq_lens_decoder,
                              seq_lens_this_time, padding_offsets=None,
                              cum_offsets=None, cu_seqlens_q=None,
                              cu_seqlens_k=None, block_tables=None,
                              max_seq_len=None, block_size=None,
                              use_neox_rotary_style=False, num_heads=None,
                              kv_num_heads=None, head_dim=None, **kwargs):
    """Paged/blocked KV-cache attention (reference: the 2.6-era serving op
    paddle.incubate.nn.functional.block_multihead_attention — unverified,
    SURVEY.md §0/§2.5).

    TPU-native path: prefill rows run the varlen Pallas flash kernel over
    the packed tokens; decode rows run the paged Pallas kernel whose
    BlockSpec index maps dereference the per-sequence block tables in
    SMEM (``ops/pallas/paged_attention``). K/V of the new tokens are
    scattered into the shared block pool; ``key_cache``/``value_cache``
    Tensors are updated in place (reference mutation semantics).

    Args (core subset):
        qkv: (total_tokens, (H + 2*HK) * D) packed projections.
        key_cache/value_cache: (num_blocks, block_size, HK, D) pools.
        seq_lens_encoder: (B,) prefill token counts (0 for decode rows).
        seq_lens_decoder: (B,) tokens already in cache (decode rows).
        seq_lens_this_time: (B,) tokens entering this call per sequence.
        cu_seqlens_q/k: (B+1,) prefix sums of seq_lens_this_time.
        block_tables: (B, max_blocks) int32 pool block ids.
    Returns the attention output (total_tokens, H * D).
    """
    import numpy as np
    import jax.numpy as jnp
    from ....ops.pallas.paged_attention import paged_decode_attention
    from ....ops.pallas.varlen_flash_attention import varlen_flash_attention
    from ....tensor._helpers import apply

    # Activation-quant / int8-KV-cache epilogues (round-5, reference
    # fused_multi_transformer int8 variant — unverified, SURVEY.md §0).
    # Conventions (paddle quant-op style, multipliers):
    #   qkv_out_scale ((H+2HK)*D,): DEQUANT multiplier applied to the
    #     incoming qkv (the int32/int8 projection output) BEFORE bias.
    #   cache_k/v_quant_scales (HK,): QUANT multipliers — the pool holds
    #     clip(round(k * qs), -128, 127) int8; cache_k/v_dequant_scales
    #     default to 1/quant_scales and are applied inside the paged
    #     kernel (prefill gathers dequantize the same way).
    #   out_shift/out_smooth (H*D,): smooth-quant epilogue
    #     (out + shift) * smooth applied to the attention output.
    #   out_scale (scalar > 0): output quantized to int8 as
    #     clip(round(out / out_scale), -128, 127).
    qkv_out_scale = kwargs.get("qkv_out_scale")
    cache_k_qs = kwargs.get("cache_k_quant_scales")
    cache_v_qs = kwargs.get("cache_v_quant_scales")
    cache_k_ds = kwargs.get("cache_k_dequant_scales")
    cache_v_ds = kwargs.get("cache_v_dequant_scales")
    out_shift = kwargs.get("out_shift")
    out_smooth = kwargs.get("out_smooth")
    out_scale = kwargs.get("out_scale", -1)
    quant_cache = cache_k_qs is not None or cache_v_qs is not None
    if quant_cache and (cache_k_qs is None or cache_v_qs is None):
        raise ValueError(
            "int8 KV cache needs BOTH cache_k_quant_scales and "
            "cache_v_quant_scales")
    # DYNAMIC per-row scale pools (the serving engine's int8 paged
    # pools): (num_blocks, block_size, HK) float32 Tensors holding one
    # symmetric abs-max scale per written row. This call quantizes its
    # new tokens' K/V rows in-graph, scatters the scales beside the
    # int8 values, and dequantizes every gathered context row by its
    # OWN scale — and mutates the scale-pool Tensors in place exactly
    # like key_cache/value_cache.
    cache_k_sp = kwargs.get("cache_k_scale_pool")
    cache_v_sp = kwargs.get("cache_v_scale_pool")
    dyn_quant = cache_k_sp is not None or cache_v_sp is not None
    if dyn_quant and (cache_k_sp is None or cache_v_sp is None):
        raise ValueError(
            "dynamic int8 KV cache needs BOTH cache_k_scale_pool and "
            "cache_v_scale_pool")
    if dyn_quant and quant_cache:
        raise ValueError(
            "pass either static cache_k/v_quant_scales or per-row "
            "cache_k/v_scale_pool, not both")
    # rope/bias fusion (reference contract: applied INSIDE the op, to
    # this call's new q/k tokens at their absolute cache positions):
    #   rotary_embs: (2, max_seq_len, head_dim//2) — [0]=cos, [1]=sin
    #   qkv_bias:    ((H + 2*HK) * D,)
    rotary_embs = kwargs.get("rotary_embs")
    qkv_bias = kwargs.get("qkv_bias")
    qkv = ensure_tensor(qkv)
    key_cache = ensure_tensor(key_cache)
    value_cache = ensure_tensor(value_cache)
    if dyn_quant:
        cache_k_sp = ensure_tensor(cache_k_sp)
        cache_v_sp = ensure_tensor(cache_v_sp)
    kc_dt = str(key_cache._value.dtype)
    vc_dt = str(value_cache._value.dtype)
    if kc_dt != vc_dt:
        raise ValueError(
            f"key_cache ({kc_dt}) and value_cache ({vc_dt}) dtypes "
            f"must match")
    if (quant_cache or dyn_quant) and kc_dt != "int8":
        raise ValueError(
            f"cache quant scales given but the cache pools are "
            f"{kc_dt}, not int8")
    if not quant_cache and not dyn_quant and kc_dt == "int8":
        raise ValueError(
            "int8 cache pools need cache_k/v_quant_scales or "
            "cache_k/v_scale_pool")
    if num_heads is None or kv_num_heads is None:
        raise ValueError(
            "block_multihead_attention requires num_heads/kv_num_heads "
            "(the packed qkv layout is ambiguous without them)")
    h, hk = int(num_heads), int(kv_num_heads)
    bs = int(key_cache._value.shape[1])
    if head_dim is None:
        head_dim = qkv._value.shape[-1] // (h + 2 * hk)
    d = int(head_dim)

    this_time = np.asarray(ensure_tensor(seq_lens_this_time)._value)
    dec_lens = np.asarray(ensure_tensor(seq_lens_decoder)._value)
    tables = ensure_tensor(block_tables)._value
    total = int(this_time.sum())
    b = len(this_time)

    def split_qkv(v):
        q = v[:, : h * d].reshape(-1, h, d)
        k = v[:, h * d : (h + hk) * d].reshape(-1, hk, d)
        val = v[:, (h + hk) * d :].reshape(-1, hk, d)
        return q, k, val

    # Row routing (host-side: lens are serving metadata, concrete in the
    # eager serving loop): decode rows contribute one token; prefill rows
    # (including CHUNKED prefill continuing a cached context) contribute
    # this_time tokens and attend over cache + new via the varlen kernel's
    # bottom-right causal alignment.
    enc_lens = np.asarray(ensure_tensor(seq_lens_encoder)._value)
    active = this_time > 0  # finished/inactive slots contribute nothing
    is_prefill_row = ((this_time > 1) | (enc_lens > 0)) & active
    if dyn_quant:
        # per-row scale pools: the Pallas paged-decode kernel only
        # supports STATIC per-head scales, so decode rows route through
        # the varlen gather path as 1-token prefill rows (bottom-right
        # causal alignment attends their full dequantized context)
        is_prefill_row = active
    cu_all = np.concatenate([[0], np.cumsum(this_time)]).astype(np.int32)
    tbl_np = np.asarray(tables)

    # every new token's pool slot (both modes write the same way)
    seq_of_tok = np.repeat(np.arange(b), this_time).astype(np.int32)
    pos_in_seq = (np.arange(total) - cu_all[seq_of_tok]).astype(np.int32)
    abs_pos = (dec_lens[seq_of_tok] + pos_in_seq).astype(np.int32)
    blk_ids = jnp.asarray(
        tbl_np[seq_of_tok, abs_pos // bs].astype(np.int32))
    offs = jnp.asarray((abs_pos % bs).astype(np.int32))

    pre_rows = np.nonzero(is_prefill_row)[0]
    dec_rows = np.nonzero(~is_prefill_row & active)[0]
    # token indices of each group, in packed order
    pre_tok = np.concatenate(
        [np.arange(cu_all[i], cu_all[i + 1]) for i in pre_rows]
    ).astype(np.int32) if len(pre_rows) else np.zeros(0, np.int32)
    dec_tok = cu_all[dec_rows].astype(np.int32)  # one token per row

    # prefill attention context: cached tokens (gathered from the pool)
    # followed by this call's new tokens, per row
    ctx_lens = (dec_lens[pre_rows] + this_time[pre_rows]).astype(np.int32)
    cu_q_pre = np.concatenate(
        [[0], np.cumsum(this_time[pre_rows])]).astype(np.int32)
    cu_k_pre = np.concatenate([[0], np.cumsum(ctx_lens)]).astype(np.int32)
    ctx_seq = np.repeat(pre_rows, ctx_lens).astype(np.int32)
    ctx_pos = (np.arange(int(ctx_lens.sum()), dtype=np.int32)
               - cu_k_pre[np.repeat(np.arange(len(pre_rows)), ctx_lens)])
    ctx_blk = jnp.asarray(
        tbl_np[ctx_seq, ctx_pos // bs].astype(np.int32)) \
        if len(pre_rows) else None
    ctx_off = jnp.asarray((ctx_pos % bs).astype(np.int32)) \
        if len(pre_rows) else None

    dec_positions = jnp.asarray(dec_lens[dec_rows], jnp.int32)
    dec_tbl = jnp.asarray(tbl_np[dec_rows]) if len(dec_rows) else None

    if rotary_embs is not None:
        # JAX gathers CLAMP out-of-bounds indices — generation past the
        # rope table would silently reuse the last angle forever
        table_len = int(ensure_tensor(rotary_embs)._value.shape[1])
        if total and int(abs_pos.max()) >= table_len:
            raise ValueError(
                f"block_multihead_attention: token position "
                f"{int(abs_pos.max())} exceeds rotary_embs table length "
                f"{table_len}")

    abs_pos_j = jnp.asarray(abs_pos)

    def _f32_vec(t, n):
        return (None if t is None
                else jnp.asarray(ensure_tensor(t)._value,
                                 jnp.float32).reshape(n))

    qkv_scale_v = _f32_vec(qkv_out_scale, (h + 2 * hk) * d)
    k_qs_v = _f32_vec(cache_k_qs, hk)
    v_qs_v = _f32_vec(cache_v_qs, hk)
    k_ds_v = _f32_vec(cache_k_ds, hk) if cache_k_ds is not None else (
        None if k_qs_v is None else 1.0 / k_qs_v)
    v_ds_v = _f32_vec(cache_v_ds, hk) if cache_v_ds is not None else (
        None if v_qs_v is None else 1.0 / v_qs_v)
    out_shift_v = _f32_vec(out_shift, h * d)
    out_smooth_v = _f32_vec(out_smooth, h * d)
    out_scale_f = float(out_scale) if out_scale is not None else -1.0

    def fn(qkv_v, kp, vp, *fused):
        fused = list(fused)
        ksp = fused.pop(0) if dyn_quant else None
        vsp = fused.pop(0) if dyn_quant else None
        rot = fused.pop(0) if rotary_embs is not None else None
        bias = fused.pop(0) if qkv_bias is not None else None
        if qkv_scale_v is not None:
            # dequantize the projection output (reference: the int8 gemm
            # emits int32; scale BEFORE the bias add)
            qkv_v = qkv_v.astype(jnp.float32) * qkv_scale_v[None, :]
        if bias is not None:
            qkv_v = qkv_v + bias.astype(qkv_v.dtype)[None, :]
        q, k_new, v_new = split_qkv(qkv_v)
        if rot is not None:
            from ....nn.functional.rope import apply_rotary_emb

            cos, sin = rot[0], rot[1]  # (max_seq, D/2)
            neox = bool(use_neox_rotary_style)
            q = apply_rotary_emb(q[None], cos, sin, neox=neox,
                                 position_ids=abs_pos_j[None])[0]
            k_new = apply_rotary_emb(k_new[None], cos, sin, neox=neox,
                                     position_ids=abs_pos_j[None])[0]
        ksp2 = vsp2 = None
        if dyn_quant:
            from ....nn.quant import quantize_kv_rows

            # per-row symmetric quant — the SAME helper the serving
            # quantum's write sites use, so a token's quantized pool row
            # (value AND scale) is identical no matter which path
            # (chunked prefill, decode quantum, spec round) wrote it
            k_store, k_sc = quantize_kv_rows(k_new)   # (T,HK,D)/(T,HK)
            v_store, v_sc = quantize_kv_rows(v_new)
            ksp2 = ksp.at[blk_ids, offs].set(k_sc)
            vsp2 = vsp.at[blk_ids, offs].set(v_sc)
        elif quant_cache:
            k_store = jnp.clip(
                jnp.round(k_new.astype(jnp.float32)
                          * k_qs_v[None, :, None]), -128, 127
            ).astype(jnp.int8)
            v_store = jnp.clip(
                jnp.round(v_new.astype(jnp.float32)
                          * v_qs_v[None, :, None]), -128, 127
            ).astype(jnp.int8)
        else:
            k_store = k_new.astype(kp.dtype)
            v_store = v_new.astype(vp.dtype)
        kp2 = kp.at[blk_ids, offs].set(k_store)
        vp2 = vp.at[blk_ids, offs].set(v_store)
        out = jnp.zeros((total, h, d), q.dtype)
        if len(pre_rows):
            q_pre = q[jnp.asarray(pre_tok)]
            # gather each prefill row's full context (cache + new) from
            # the updated pool
            k_ctx = kp2[ctx_blk, ctx_off]
            v_ctx = vp2[ctx_blk, ctx_off]
            if dyn_quant:
                k_ctx = (k_ctx.astype(jnp.float32)
                         * ksp2[ctx_blk, ctx_off][..., None])
                v_ctx = (v_ctx.astype(jnp.float32)
                         * vsp2[ctx_blk, ctx_off][..., None])
            elif quant_cache:
                k_ctx = k_ctx.astype(jnp.float32) * k_ds_v[None, :, None]
                v_ctx = v_ctx.astype(jnp.float32) * v_ds_v[None, :, None]
            k_ctx = k_ctx.astype(q.dtype)
            v_ctx = v_ctx.astype(q.dtype)
            o_pre = varlen_flash_attention(
                q_pre, k_ctx, v_ctx, jnp.asarray(cu_q_pre),
                jnp.asarray(cu_k_pre), causal=True)
            out = out.at[jnp.asarray(pre_tok)].set(o_pre)
        if len(dec_rows):
            o_dec = paged_decode_attention(
                q[jnp.asarray(dec_tok)], kp2, vp2, dec_tbl,
                dec_positions + 1,
                k_scale=k_ds_v if quant_cache else None,
                v_scale=v_ds_v if quant_cache else None)
            out = out.at[jnp.asarray(dec_tok)].set(o_dec)
        out_flat = out.reshape(total, h * d)
        if out_shift_v is not None:
            out_flat = out_flat + out_shift_v[None, :].astype(out_flat.dtype)
        if out_smooth_v is not None:
            out_flat = out_flat * out_smooth_v[None, :].astype(out_flat.dtype)
        if out_scale_f > 0:
            out_flat = jnp.clip(
                jnp.round(out_flat.astype(jnp.float32) / out_scale_f),
                -128, 127).astype(jnp.int8)
        if dyn_quant:
            return out_flat, kp2, vp2, ksp2, vsp2
        return out_flat, kp2, vp2

    fused_args = []
    if dyn_quant:
        fused_args.append(cache_k_sp)
        fused_args.append(cache_v_sp)
    if rotary_embs is not None:
        fused_args.append(ensure_tensor(rotary_embs))
    if qkv_bias is not None:
        fused_args.append(ensure_tensor(qkv_bias))
    if dyn_quant:
        out, new_k, new_v, new_ks, new_vs = apply(
            fn, qkv, key_cache, value_cache, *fused_args,
            op_name="block_multihead_attention",
        )
        key_cache._value = new_k._value
        value_cache._value = new_v._value
        cache_k_sp._value = new_ks._value
        cache_v_sp._value = new_vs._value
        return out
    out, new_k, new_v = apply(
        fn, qkv, key_cache, value_cache, *fused_args,
        op_name="block_multihead_attention",
    )
    key_cache._value = new_k._value
    value_cache._value = new_v._value
    return out


__all__.append("block_multihead_attention")
