"""paddle.incubate.nn — fused layers (reference:
python/paddle/incubate/nn/layer/fused_transformer.py and
paddle/fluid/operators/fused/fused_multi_transformer_op.cu — unverified,
SURVEY.md §0/§2.5).

``FusedMultiTransformer`` is the decode-path flagship: the WHOLE decoder
stack runs as one XLA program — per-layer weights are stacked with a
leading layer dim and the stack is a ``lax.scan``, so a 32-layer decode
step is a single dispatch (the reference gets this with one mega CUDA op;
XLA gets it with scan + the Pallas decode-attention kernel).
"""
from .fused_transformer import FusedMultiTransformer  # noqa: F401
from . import functional  # noqa: F401

__all__ = ["FusedMultiTransformer", "functional"]
