"""paddle.incubate.optimizer — LookAhead / ModelAverage / EMA (reference:
python/paddle/incubate/optimizer/{lookahead,modelaverage}.py — unverified,
SURVEY.md §0).

All three are parameter-buffer transforms around an inner optimizer:
state lives as host-held jax arrays updated with fused jnp expressions
(one jitted elementwise pass per step — no per-param Python dispatch)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["LookAhead", "ModelAverage", "ExponentialMovingAverage"]


class LookAhead:
    """k-step lookahead: every k inner steps, slow weights interpolate
    toward fast weights and both sync (Zhang et al., reference
    incubate.optimizer.LookAhead)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step = 0
        self._slow = None
        self._interp = jax.jit(
            lambda slow, fast: [
                s + self.alpha * (f - s) for s, f in zip(slow, fast)
            ]
        )

    def _params(self):
        return list(self.inner_optimizer._parameter_list or [])

    def step(self):
        params = self._params()
        if self._slow is None:
            self._slow = [p._value for p in params]
        self.inner_optimizer.step()
        self._step += 1
        if self._step % self.k == 0:
            new_slow = self._interp(self._slow, [p._value for p in params])
            self._slow = new_slow
            states = getattr(self.inner_optimizer, "_states", {})
            for p, v in zip(params, new_slow):
                p._value = v
                # multi_precision: the fp32 master is the live copy the
                # next update reads — sync it too or the interpolation
                # is silently discarded
                st = states.get(id(p))
                if st is not None and "master" in st:
                    st["master"] = v.astype(st["master"].dtype)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "step": self._step, "slow": self._slow}

    def set_state_dict(self, state):
        self.inner_optimizer.set_state_dict(state["inner"])
        self._step = state.get("step", 0)
        self._slow = state.get("slow")


class _AveragerBase:
    def __init__(self, parameters):
        self._params = list(parameters)
        self._avg = None
        self._backup = None

    def _values(self):
        return [p._value for p in self._params]

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (eval); restore() undoes it."""
        if self._avg is None:
            return
        self._backup = self._values() if need_restore else None
        for p, a in zip(self._params, self._avg):
            p._value = a.astype(p._value.dtype)

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._params, self._backup):
                p._value = b
            self._backup = None


class ModelAverage(_AveragerBase):
    """Running average of parameters over an accumulation window
    (reference incubate.optimizer.ModelAverage; the window controls are
    accepted for parity — the average here is the running mean of every
    ``step()`` call, which is what the reference degrades to when the
    window covers training)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError(
                "ModelAverage requires parameters= (this backend has no "
                "global parameter registry to default to)"
            )
        super().__init__(parameters)
        self._n = 0
        self._acc = jax.jit(
            lambda avg, vals, n: [
                a + (v.astype(jnp.float32) - a) / (n + 1)
                for a, v in zip(avg, vals)
            ]
        )

    def step(self):
        vals = self._values()
        if self._avg is None:
            self._avg = [v.astype(jnp.float32) for v in vals]
            self._n = 1
            return
        self._avg = self._acc(self._avg, vals, jnp.float32(self._n))
        self._n += 1

    # paddle calls minimize/step on the wrapped optimizer externally


class ExponentialMovingAverage(_AveragerBase):
    """EMA of parameters: shadow = decay * shadow + (1-decay) * param
    (reference paddle.incubate ExponentialMovingAverage)."""

    def __init__(self, parameters, decay=0.999, name=None):
        super().__init__(parameters)
        self.decay = float(decay)
        self._ema = jax.jit(
            lambda avg, vals: [
                self.decay * a + (1 - self.decay) * v.astype(jnp.float32)
                for a, v in zip(avg, vals)
            ]
        )

    def update(self):
        vals = self._values()
        if self._avg is None:
            self._avg = [v.astype(jnp.float32) for v in vals]
            return
        self._avg = self._ema(self._avg, vals)

    step = update
