"""paddle.quantization — QAT / PTQ front-end (reference:
python/paddle/quantization/ — unverified, SURVEY.md §0).

Workflow parity with the reference:

    q_config = QuantConfig(activation=FakeQuanterWithAbsMax(),
                           weight=FakeQuanterWithAbsMax())
    qat = QAT(q_config)
    q_model = qat.quantize(model)       # Linear -> QuantedLinear (STE)
    ... train ...
    infer = qat.convert(q_model)        # -> weight-only int8 layers

    ptq = PTQ(q_config)
    q_model = ptq.quantize(model)       # observers record abs-max
    ... run calibration batches ...
    infer = ptq.convert(q_model)

All quantized math lives in ``paddle.nn.quant`` (fake-quant STE ops,
int8 weight-only matmul, a8w8 int32-accumulation dot)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear
from ..nn.quant import (
    fake_quantize_dequantize_abs_max, QuantizedLinear, weight_quantize,
)
from ..tensor._helpers import Tensor

__all__ = [
    "QuantConfig", "QAT", "PTQ",
    "FakeQuanterWithAbsMax", "AbsmaxObserver",
    "QuantedLinear",
]


class FakeQuanterWithAbsMax:
    """Quanter factory: per-tensor abs-max fake quant with STE grad."""

    def __init__(self, quant_bits=8, name=None):
        self.quant_bits = quant_bits

    def __call__(self, x):
        return fake_quantize_dequantize_abs_max(x, bits=self.quant_bits)


class AbsmaxObserver:
    """PTQ observer: tracks the running max |x| over calibration runs."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self.absmax = 0.0

    def observe(self, x):
        v = x._value if isinstance(x, Tensor) else x
        self.absmax = max(self.absmax, float(jnp.max(jnp.abs(v))))

    def scale(self):
        qmax = float(2 ** (self.quant_bits - 1) - 1)
        return max(self.absmax, 1e-8) / qmax


class QuantConfig:
    """Global activation/weight quanter config (the reference's
    per-layer/per-type maps degrade to this global default; extend via
    ``add_type_config`` later if needed)."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight


class QuantedLinear(Layer):
    """QAT wrapper: fake-quant weight (and optionally input) around a
    live Linear — grads flow via STE to the float master weight."""

    def __init__(self, linear: Linear, q_config: QuantConfig):
        super().__init__()
        self._inner = linear
        self._act_quanter = q_config.activation
        self._w_quanter = q_config.weight

    def forward(self, x):
        from ..nn import functional as F

        w = self._inner.weight
        if self._w_quanter is not None:
            w = self._w_quanter(w)
        if self._act_quanter is not None:
            x = self._act_quanter(x)
        return F.linear(x, w, self._inner.bias)


class _ObservedLinear(Layer):
    """PTQ wrapper: plain forward + activation observation."""

    def __init__(self, linear: Linear, q_config: QuantConfig):
        super().__init__()
        self._inner = linear
        self.observer = AbsmaxObserver(
            getattr(q_config.activation, "quant_bits", 8) or 8
        )

    def forward(self, x):
        self.observer.observe(x)
        return self._inner(x)


def _replace_linears(layer, factory):
    for name, sub in list(layer._sub_layers.items()):
        if isinstance(sub, Linear):
            layer._sub_layers[name] = factory(sub)
        else:
            _replace_linears(sub, factory)
    return layer


def _convert_wrapped(layer):
    for name, sub in list(layer._sub_layers.items()):
        if isinstance(sub, _ObservedLinear):
            # calibration observed the activation range → a8w8 path
            act_scale = sub.observer.scale() if sub.observer.absmax > 0 \
                else None
            layer._sub_layers[name] = QuantizedLinear.from_linear(
                sub._inner, act_scale=act_scale
            )
        elif isinstance(sub, QuantedLinear):
            layer._sub_layers[name] = QuantizedLinear.from_linear(sub._inner)
        else:
            _convert_wrapped(sub)
    return layer


class QAT:
    def __init__(self, q_config: QuantConfig):
        self._config = q_config

    def quantize(self, model, inplace=True):
        return _replace_linears(
            model, lambda lin: QuantedLinear(lin, self._config)
        )

    def convert(self, model, inplace=True):
        return _convert_wrapped(model)


class PTQ:
    def __init__(self, q_config: QuantConfig = None):
        self._config = q_config or QuantConfig()

    def quantize(self, model, inplace=True):
        return _replace_linears(
            model, lambda lin: _ObservedLinear(lin, self._config)
        )

    def convert(self, model, inplace=True):
        return _convert_wrapped(model)
