"""paddle.autograd namespace (reference: python/paddle/autograd/)."""
from ..core.autograd import backward, grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled", "is_grad_enabled", "PyLayer"]


class PyLayerContext:
    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        # method, matching paddle.autograd.PyLayerContext.saved_tensor()
        return self._saved


class PyLayer:
    """Custom-op autograd extension point (reference: paddle.autograd.PyLayer).

    Subclasses define static forward(ctx, *args) and backward(ctx, *grads)
    written in paddle_tpu ops; apply() stitches them into the tape via a
    jax.custom_vjp-free manual node.
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.tensor import Tensor
        from ..core import autograd as ag
        import weakref
        import jax

        ctx = PyLayerContext()
        with ag.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = isinstance(out, Tensor)
        outs = [out] if single else list(out)
        diff_inputs = [a for a in args if isinstance(a, Tensor) and not a.stop_gradient]
        if ag.is_grad_enabled() and diff_inputs:
            import jax.numpy as jnp

            def vjp_fn(cots):
                cots = cots if isinstance(cots, tuple) else (cots,)
                with ag.no_grad():
                    gin = cls.backward(ctx, *[Tensor(c, stop_gradient=True) for c in cots])
                gin = (gin,) if isinstance(gin, Tensor) else tuple(gin)
                # align returned grads with diff inputs (paddle returns one
                # grad per forward tensor input, in order)
                t_inputs = [a for a in args if isinstance(a, Tensor)]
                grads = []
                for t, g in zip(t_inputs, gin):
                    if not t.stop_gradient:
                        grads.append(g._value if isinstance(g, Tensor) else g)
                return tuple(grads)

            def taped_vjp(cot_tensors):
                # create_graph path: run the user's backward with grad
                # recording ON so the produced grads stay on the tape
                gin = cls.backward(ctx, *cot_tensors)
                gin = (gin,) if isinstance(gin, Tensor) else tuple(gin)
                t_inputs = [a for a in args if isinstance(a, Tensor)]
                grads = []
                for t, g in zip(t_inputs, gin):
                    if not t.stop_gradient:
                        grads.append(g)
                return tuple(grads)

            flat, treedef = jax.tree_util.tree_flatten(tuple(t._value for t in outs))
            node = ag.Node(
                vjp_fn,
                [t._ensure_slot() for t in diff_inputs],
                [],
                treedef,
                name=cls.__name__,
                taped_vjp=taped_vjp,
            )
            for t in outs:
                t._stop_gradient = False
                slot = ag.GradSlot(owner=t, node=node)
                t._slot = slot
                node.outputs.append((slot, tuple(t._value.shape), t._value.dtype))
        return out
