"""paddle.autograd namespace (reference: python/paddle/autograd/)."""
from ..core.autograd import backward, grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled", "is_grad_enabled", "PyLayer"]


class PyLayerContext:
    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        # method, matching paddle.autograd.PyLayerContext.saved_tensor()
        return self._saved


class PyLayer:
    """Custom-op autograd extension point (reference: paddle.autograd.PyLayer).

    Subclasses define static forward(ctx, *args) and backward(ctx, *grads)
    written in paddle_tpu ops; apply() stitches them into the tape via a
    jax.custom_vjp-free manual node.
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.tensor import Tensor
        from ..core import autograd as ag
        import weakref
        import jax

        ctx = PyLayerContext()
        with ag.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = isinstance(out, Tensor)
        outs = [out] if single else list(out)
        diff_inputs = [a for a in args if isinstance(a, Tensor) and not a.stop_gradient]
        if ag.is_grad_enabled() and diff_inputs:
            import jax.numpy as jnp

            def vjp_fn(cots):
                cots = cots if isinstance(cots, tuple) else (cots,)
                with ag.no_grad():
                    gin = cls.backward(ctx, *[Tensor(c, stop_gradient=True) for c in cots])
                gin = (gin,) if isinstance(gin, Tensor) else tuple(gin)
                # align returned grads with diff inputs (paddle returns one
                # grad per forward tensor input, in order)
                t_inputs = [a for a in args if isinstance(a, Tensor)]
                grads = []
                for t, g in zip(t_inputs, gin):
                    if not t.stop_gradient:
                        grads.append(g._value if isinstance(g, Tensor) else g)
                return tuple(grads)

            def taped_vjp(cot_tensors):
                # create_graph path: run the user's backward with grad
                # recording ON so the produced grads stay on the tape
                gin = cls.backward(ctx, *cot_tensors)
                gin = (gin,) if isinstance(gin, Tensor) else tuple(gin)
                t_inputs = [a for a in args if isinstance(a, Tensor)]
                grads = []
                for t, g in zip(t_inputs, gin):
                    if not t.stop_gradient:
                        grads.append(g)
                return tuple(grads)

            flat, treedef = jax.tree_util.tree_flatten(tuple(t._value for t in outs))
            node = ag.Node(
                vjp_fn,
                [t._ensure_slot() for t in diff_inputs],
                [],
                treedef,
                name=cls.__name__,
                taped_vjp=taped_vjp,
            )
            for t in outs:
                t._stop_gradient = False
                slot = ag.GradSlot(owner=t, node=node)
                t._slot = slot
                node.outputs.append((slot, tuple(t._value.shape), t._value.dtype))
        return out


def _functionalize(func):
    """Wrap a Tensor-level callable as a pure jax-value function with the
    output pytree preserved (Tensors become raw leaves)."""
    from ..core.tensor import Tensor
    from ..core import autograd as ag
    import jax as _jax

    def pure(*vals):
        with ag.no_grad():
            out = func(*[Tensor(v, stop_gradient=True) for v in vals])
        return _jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda x: isinstance(x, Tensor),
        )

    return pure


def _run_taped(fn, xs_list, op_name, create_graph):
    """Evaluate a pure jax transform through the dispatch seam: the
    result is ON the tape when inputs are tracked, which is what makes
    create_graph (higher-order use) work; create_graph=False detaches."""
    from ..core.dispatch import apply as dispatch_apply
    from ..core.tensor import Tensor
    import jax as _jax

    out = dispatch_apply(fn, *xs_list, op_name=op_name)
    if not create_graph:
        out = _jax.tree_util.tree_map(
            lambda t: Tensor(t._value, stop_gradient=True)
            if isinstance(t, Tensor) else t,
            out, is_leaf=lambda x: isinstance(x, Tensor),
        )
    return out


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """paddle.autograd.jacobian — dense Jacobian of func at xs via
    jax.jacrev over the functionalized graph (reference:
    python/paddle/autograd/functional.py — unverified).

    ``create_graph=True`` keeps the Jacobian on the tape (differentiable
    again). Unused inputs yield zero blocks (this backend cannot detect
    graph non-participation, so ``allow_unused`` has no effect)."""
    from ..core.tensor import Tensor
    import jax as _jax

    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    pure = _functionalize(func)
    argnums = tuple(range(len(xs_list)))

    def fn(*vals):
        jac = _jax.jacrev(pure, argnums=argnums)(*vals)
        return jac[0] if single else jac

    return _run_taped(fn, xs_list, "jacobian", create_graph)


def hessian(func, xs, create_graph=False, allow_unused=False):
    """paddle.autograd.hessian — Hessian of a scalar-valued func (see
    jacobian for create_graph/allow_unused semantics)."""
    from ..core.tensor import Tensor
    import jax as _jax

    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    pure = _functionalize(func)
    argnums = tuple(range(len(xs_list)))

    def fn(*vals):
        hes = _jax.hessian(pure, argnums=argnums)(*vals)
        return hes[0][0] if single else hes

    return _run_taped(fn, xs_list, "hessian", create_graph)


def vjp(func, xs, v=None):
    """paddle.autograd.vjp → (outputs, vjp_result); pytree outputs keep
    their structure, ``v`` must mirror it, and both results stay on the
    tape (differentiable again) when inputs are tracked."""
    import jax as _jax
    import jax.numpy as _jnp
    from ..core.tensor import Tensor

    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    pure = _functionalize(func)

    if v is not None:
        cot_tree = _jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else _jnp.asarray(t),
            v, is_leaf=lambda x: isinstance(x, Tensor),
        )
    else:
        cot_tree = None

    def fn(*vals):
        out, vjp_fn = _jax.vjp(pure, *vals)
        cot = (_jax.tree_util.tree_map(_jnp.ones_like, out)
               if cot_tree is None else cot_tree)
        n_out = len(_jax.tree_util.tree_leaves(out))
        n_v = len(_jax.tree_util.tree_leaves(cot))
        if n_out != n_v:
            raise ValueError(
                f"vjp: v has {n_v} leaves but func produced {n_out} outputs"
            )
        grads = vjp_fn(cot)
        return out, (grads[0] if single else tuple(grads))

    out, grads = _run_taped(fn, xs_list, "vjp", create_graph=True)
    return out, grads


def jvp(func, xs, v=None):
    """paddle.autograd.jvp → (outputs, jvp_result); results stay on the
    tape when inputs are tracked."""
    import jax as _jax
    import jax.numpy as _jnp
    from ..core.tensor import Tensor

    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    pure = _functionalize(func)
    if v is not None:
        v_list = v if isinstance(v, (list, tuple)) else [v]
        if len(v_list) != len(xs_list):
            raise ValueError(
                f"jvp: v has {len(v_list)} entries for {len(xs_list)} inputs"
            )
        tangents = tuple(
            t._value if isinstance(t, Tensor) else _jnp.asarray(t)
            for t in v_list
        )
    else:
        tangents = None

    def fn(*vals):
        tang_in = (tuple(_jnp.ones_like(p) for p in vals)
                   if tangents is None else tangents)
        return _jax.jvp(pure, tuple(vals), tang_in)

    out, tang = _run_taped(fn, xs_list, "jvp", create_graph=True)
    return out, tang


__all__ += ["jacobian", "hessian", "vjp", "jvp"]
