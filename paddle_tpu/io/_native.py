"""ctypes loader for the native io core (csrc/paddle_tpu_io.cc).

Resolution order: a prebuilt ``libpaddle_tpu_io.so`` next to this file,
then a cached build under ``~/.cache/paddle_tpu``, then a one-shot g++
compile of ``csrc/`` when a toolchain is present (dev checkouts). All
failures degrade to ``lib() is None`` — pure-Python paths keep working.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys

import numpy as np

_LIB = None
_TRIED = False


def _candidate_paths():
    here = os.path.dirname(os.path.abspath(__file__))
    yield os.path.join(here, "libpaddle_tpu_io.so")
    yield os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu",
        "libpaddle_tpu_io.so",
    )


def _source_path():
    # dev checkout: csrc/ sits two levels above paddle_tpu/io/
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    p = os.path.join(root, "csrc", "paddle_tpu_io.cc")
    return p if os.path.exists(p) else None


def _try_build(out_path):
    src = _source_path()
    if src is None:
        return None
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        src, "-o", out_path,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
        return out_path
    except Exception as e:  # no toolchain / failed compile → Python path
        print(f"paddle_tpu: native io build skipped ({e})", file=sys.stderr)
        return None


def _bind(path):
    lib = ctypes.CDLL(path)
    lib.ptpu_gather_rows.restype = ctypes.c_int
    lib.ptpu_gather_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int,
    ]
    lib.ptpu_shuffle_indices.restype = None
    lib.ptpu_shuffle_indices.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64,
    ]
    lib.ptpu_pack_varlen.restype = ctypes.c_int
    lib.ptpu_pack_varlen.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
    ]
    lib.ptpu_version.restype = ctypes.c_int
    if lib.ptpu_version() != 1:
        raise RuntimeError("native io core ABI mismatch")
    return lib


def lib():
    """The loaded native library, or None (pure-Python fallback)."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    for path in _candidate_paths():
        if os.path.exists(path):
            try:
                _LIB = _bind(path)
                return _LIB
            except Exception:
                continue
    built = _try_build(list(_candidate_paths())[-1])
    if built:
        try:
            _LIB = _bind(built)
        except Exception:
            _LIB = None
    return _LIB


def _n_threads():
    return min(8, os.cpu_count() or 1)


def gather_rows(src: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Native batch assembly: ``src[indices]`` for a C-contiguous array,
    multithreaded row memcpy. Falls back to numpy fancy indexing."""
    L = lib()
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    if L is None or not src.flags.c_contiguous or src.ndim < 1:
        return src[idx]
    row_bytes = int(src.dtype.itemsize * np.prod(src.shape[1:], dtype=np.int64))
    if row_bytes == 0:
        return src[idx]
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    rc = L.ptpu_gather_rows(
        src.ctypes.data, src.shape[0], row_bytes,
        idx.ctypes.data, len(idx), out.ctypes.data, _n_threads(),
    )
    if rc != 0:
        raise IndexError("gather_rows: index out of range")
    return out


def shuffle_indices(n: int, seed: int) -> np.ndarray:
    """Deterministic native Fisher–Yates permutation of arange(n)."""
    buf = np.arange(n, dtype=np.int64)
    L = lib()
    if L is None:
        rng = np.random.RandomState(seed & 0x7FFFFFFF)
        rng.shuffle(buf)
        return buf
    L.ptpu_shuffle_indices(buf.ctypes.data, n, seed)
    return buf


def pack_varlen(rows, max_len: int, pad_id: int = 0):
    """Pack a list of int sequences → (batch int32 [n, max_len], lengths
    int32 [n]); truncates rows longer than max_len."""
    out = np.empty((len(rows), max_len), np.int32)
    lengths = np.empty((len(rows),), np.int32)
    L = lib()
    if L is None:
        for i, r in enumerate(rows):
            a = np.asarray(r, dtype=np.int32)[:max_len]
            lengths[i] = len(a)
            out[i, : len(a)] = a
            out[i, len(a):] = pad_id
        return out, lengths
    flat = np.concatenate(
        [np.asarray(r, dtype=np.int32) for r in rows]
    ) if rows else np.zeros((0,), np.int32)
    offsets = np.zeros(len(rows) + 1, np.int64)
    np.cumsum([len(r) for r in rows], out=offsets[1:])
    rc = L.ptpu_pack_varlen(
        flat.ctypes.data, offsets.ctypes.data, len(rows), max_len,
        pad_id, out.ctypes.data, lengths.ctypes.data, _n_threads(),
    )
    if rc != 0:
        raise ValueError("pack_varlen: bad arguments")
    return out, lengths
