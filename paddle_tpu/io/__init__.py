"""paddle.io: Dataset / Sampler / DataLoader (reference:
python/paddle/io/ — unverified, SURVEY.md §0).

The reference's multiprocess workers + LoDTensorBlockingQueue become a
background prefetch thread feeding ``jax.device_put`` (double buffering —
host→HBM copy overlaps compute). A C++ prefetch core slots in behind the
same API (csrc/, loaded when built).
"""
from __future__ import annotations

import itertools
import os
import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from ..core.random import default_generator

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler", "DataLoader",
    "get_worker_info", "default_collate_fn", "pack_varlen",
]


def pack_varlen(rows, max_len, pad_id=0):
    """Pad/pack variable-length int sequences into a dense int32 batch +
    lengths (native multithreaded kernel when csrc/ is built)."""
    from . import _native

    out, lengths = _native.pack_varlen(rows, max_len, pad_id)
    return Tensor(out), Tensor(lengths)


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(ds) for ds in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset, self.indices = dataset, list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(total * f) for f in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.RandomState(
        default_generator.initial_seed() & 0x7FFFFFFF
    ).permutation(total)
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off : off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        if n >= (1 << 16):
            # epoch shuffles of large datasets: native Fisher–Yates
            # (csrc/), seeded from the same global stream so runs stay
            # reproducible under paddle.seed
            from . import _native

            seed = int(np.random.randint(0, 2**31 - 1))
            return iter(
                _native.shuffle_indices(n, seed)[: self.num_samples].tolist()
            )
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(
            weights.numpy() if isinstance(weights, Tensor) else weights,
            np.float64,
        )
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(
            np.random.choice(
                len(self.weights), self.num_samples, self.replacement, p
            ).tolist()
        )

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = -(-len(dataset) // self.nranks)
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rs = np.random.RandomState(self.epoch)
            indices = rs.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


class _WorkerInfo:
    def __init__(self, id=0, num_workers=0, dataset=None):
        self.id, self.num_workers, self.dataset = id, num_workers, dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    """Stack samples into batched Tensors, matching paddle's collate."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([s.numpy() for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(group)) for group in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, ValueError):
        return False
    except PermissionError:
        return True


def _claim_worker_id(claim_dir):
    """Filesystem-based worker-id counter: O_EXCL slot files work across
    any spawn boundary (mp.Value's SemLock does not survive pickling to
    a spawned pool worker in sandboxed environments). Slots record the
    claimant's pid so a worker respawned after a pool-mate died can
    reclaim the dead slot (keeping ids < num_workers) instead of
    counting upward forever."""
    i = 0
    while True:
        slot = os.path.join(claim_dir, f"w{i}")
        try:
            return _try_claim_slot(slot, i)
        except FileNotFoundError:
            # claim_dir removed by close() while this worker was still
            # spawning (anywhere in the claim/reap sequence): the pool is
            # shutting down, nothing will consume our output — any id is
            # fine, exit the claim loop quietly
            return i
        except _SlotTaken:
            i += 1


class _SlotTaken(Exception):
    """Internal: this slot is live-owned, try the next one."""


def _try_claim_slot(slot, i):
    try:
        fd = os.open(slot, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.write(fd, str(os.getpid()).encode())
        os.close(fd)
        return i
    except FileExistsError:
        # dead claimant? take over via an exclusive reap marker so
        # only one respawned worker recycles the slot
        try:
            with open(slot) as f:
                owner = int(f.read().strip() or -1)
        except FileNotFoundError:
            raise  # claim_dir gone: let the caller exit quietly
        except (OSError, ValueError):
            owner = -1
        if owner != -1 and not _pid_alive(owner):
            try:
                rfd = os.open(
                    slot + ".reap", os.O_CREAT | os.O_EXCL | os.O_WRONLY
                )
            except FileExistsError:
                raise _SlotTaken from None
            try:
                # re-check under the marker: another reaper may have
                # recycled this slot between our read and the win
                try:
                    with open(slot) as f:
                        owner = int(f.read().strip() or -1)
                except FileNotFoundError:
                    raise
                except (OSError, ValueError):
                    owner = -1
                if owner == -1 or _pid_alive(owner):
                    raise _SlotTaken from None
                with open(slot, "w") as f:
                    f.write(str(os.getpid()))
                return i
            finally:
                os.close(rfd)
                try:
                    os.unlink(slot + ".reap")
                except FileNotFoundError:
                    pass
        raise _SlotTaken from None


def _pool_init(dataset, collate_fn, worker_init_fn, claim_dir, num_workers):
    """Spawned-worker initializer: installs the dataset/collate globals
    once per worker (pickled once, not per batch) and runs the user's
    worker_init_fn with a stable worker id.

    Workers must stay off the accelerator — the parent owns the (single)
    TPU client — so the child is pinned to the CPU backend and collation
    stays in numpy; the parent tensorizes."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    global _WORKER_DATASET, _WORKER_COLLATE, _worker_info
    _WORKER_DATASET = dataset
    _WORKER_COLLATE = collate_fn
    wid = _claim_worker_id(claim_dir) if claim_dir else 0
    _worker_info = _WorkerInfo(
        id=wid, num_workers=num_workers, dataset=dataset
    )
    if worker_init_fn is not None:
        worker_init_fn(wid)


def _collate_numpy(batch):
    """default_collate_fn that stays in numpy (worker-process side)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        return [_collate_numpy(list(g)) for g in zip(*batch)]
    if isinstance(sample, dict):
        return {k: _collate_numpy([d[k] for d in batch]) for k in sample}
    return batch


def _tensorize(tree):
    if isinstance(tree, np.ndarray):
        return Tensor(tree)
    if isinstance(tree, list):
        return [_tensorize(t) for t in tree]
    if isinstance(tree, tuple):
        return tuple(_tensorize(t) for t in tree)
    if isinstance(tree, dict):
        return {k: _tensorize(v) for k, v in tree.items()}
    return tree


def _pool_fetch(indices):
    samples = [_WORKER_DATASET[i] for i in indices]
    if _WORKER_COLLATE is None:  # default collate, numpy side
        return _collate_numpy(samples)
    return _WORKER_COLLATE(samples)


def _pool_warmup():
    return os.getpid()


def _picklable(*objs):
    import pickle

    try:
        for o in objs:
            pickle.dumps(o)
        return True
    except Exception:
        return False


class DataLoader:
    """Iterates a Dataset with batching + background prefetch.

    ``num_workers>0`` fetches batches in spawned worker *processes*
    (reference: python/paddle/io/dataloader/worker.py — unverified): the
    dataset/collate_fn ship to each worker once, batch index lists are
    dispatched with a bounded in-flight window, and results are yielded
    strictly in order. Falls back to a daemon prefetch thread when the
    dataset/collate aren't picklable or the dataset is iterable —
    spawn (not fork) is mandatory here because a forked child of a
    process with a live TPU client hangs.
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self.timeout = timeout
        self._executor = None
        self._claim_dir = None
        self._picklable_ok = None  # decided once, on first iteration
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last,
            )
            self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _native_batch_iter(self):
        """Native fast path: TensorDataset over numpy arrays + default
        collate → per-field multithreaded row gather in C++ (csrc/),
        yielding device-ready contiguous batches."""
        from . import _native

        fields = self.dataset.tensors
        for batch_idx in self.batch_sampler:
            idx = np.asarray(list(batch_idx), np.int64)
            yield [Tensor(_native.gather_rows(t, idx)) for t in fields]

    def _use_native_fast_path(self):
        from . import _native

        return (
            isinstance(self.dataset, TensorDataset)
            and self.collate_fn is default_collate_fn
            and bool(self.dataset.tensors)
            and all(isinstance(t, np.ndarray) for t in self.dataset.tensors)
            and _native.lib() is not None
        )

    def _fetch_iter(self):
        if not self._iterable_mode and self._use_native_fast_path():
            yield from self._native_batch_iter()
            return
        if self._iterable_mode:
            buf = []
            for item in self.dataset:
                buf.append(item)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not getattr(self, "drop_last", False):
                yield self.collate_fn(buf)
        else:
            for batch_idx in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in batch_idx])

    def _ensure_executor(self):
        if self._executor is None:
            import multiprocessing as mp
            from concurrent.futures import ProcessPoolExecutor

            import tempfile

            ctx = mp.get_context("spawn")
            claim_dir = self._claim_dir = tempfile.mkdtemp(prefix="pdtpu_dl_")
            collate = (None if self.collate_fn is default_collate_fn
                       else self.collate_fn)
            ex = ProcessPoolExecutor(
                max_workers=self.num_workers, mp_context=ctx,
                initializer=_pool_init,
                initargs=(self.dataset, collate, self.worker_init_fn,
                          claim_dir, self.num_workers),
            )
            # Spawn every worker NOW with the accelerator disabled in the
            # inherited env: children unpickle initargs during bootstrap
            # (before the initializer runs), and neither a Tensor-bearing
            # dataset nor a TPU-plugin sitecustomize may touch the
            # parent's (single-client) TPU from a worker.
            pinned = {
                "JAX_PLATFORMS": "cpu",
                # gates the axon sitecustomize's PJRT registration
                "PALLAS_AXON_POOL_IPS": "",
            }
            prev = {k: os.environ.get(k) for k in pinned}
            os.environ.update(pinned)
            try:
                ex.submit(_pool_warmup).result()
            finally:
                for k, v in prev.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            self._executor = ex
        return self._executor

    def _process_iter(self):
        from collections import deque

        ex = self._ensure_executor()
        window = self.prefetch_factor * self.num_workers
        pending = deque()
        try:
            for batch_idx in self.batch_sampler:
                pending.append(ex.submit(_pool_fetch, list(batch_idx)))
                if len(pending) >= window:
                    yield _tensorize(pending.popleft().result(
                        timeout=self.timeout or None))
            while pending:
                yield _tensorize(pending.popleft().result(
                    timeout=self.timeout or None))
        finally:
            if not self.persistent_workers:
                self.close()

    def close(self):
        """Shut down pool workers (also for ``persistent_workers=True``)
        and remove the worker-id claim directory. Idempotent; called
        automatically at the end of each epoch for non-persistent pools
        and from ``__del__`` otherwise."""
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None
        if self._claim_dir is not None:
            import shutil

            shutil.rmtree(self._claim_dir, ignore_errors=True)
            self._claim_dir = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._fetch_iter()
            return
        if self._picklable_ok is None:
            self._picklable_ok = (not self._iterable_mode) and _picklable(
                self.dataset, self.collate_fn, self.worker_init_fn
            )
        if self._picklable_ok:
            yield from self._process_iter()
            return
        # background-thread prefetch pipeline
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor * self.num_workers)
        sentinel = object()
        error: list = []

        def producer():
            try:
                for item in self._fetch_iter():
                    q.put(item)
            except BaseException as e:  # propagate to the consumer, don't
                error.append(e)         # silently truncate the epoch
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        t.join()
        if error:
            raise error[0]
