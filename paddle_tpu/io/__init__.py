"""paddle.io: Dataset / Sampler / DataLoader (reference:
python/paddle/io/ — unverified, SURVEY.md §0).

The reference's multiprocess workers + LoDTensorBlockingQueue become a
background prefetch thread feeding ``jax.device_put`` (double buffering —
host→HBM copy overlaps compute). A C++ prefetch core slots in behind the
same API (csrc/, loaded when built).
"""
from __future__ import annotations

import itertools
import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from ..core.random import default_generator

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler", "DataLoader",
    "get_worker_info", "default_collate_fn",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(ds) for ds in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset, self.indices = dataset, list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(total * f) for f in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.RandomState(
        default_generator.initial_seed() & 0x7FFFFFFF
    ).permutation(total)
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off : off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(
            weights.numpy() if isinstance(weights, Tensor) else weights,
            np.float64,
        )
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(
            np.random.choice(
                len(self.weights), self.num_samples, self.replacement, p
            ).tolist()
        )

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = -(-len(dataset) // self.nranks)
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rs = np.random.RandomState(self.epoch)
            indices = rs.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


class _WorkerInfo:
    def __init__(self, id=0, num_workers=0, dataset=None):
        self.id, self.num_workers, self.dataset = id, num_workers, dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    """Stack samples into batched Tensors, matching paddle's collate."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([s.numpy() for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(group)) for group in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class DataLoader:
    """Iterates a Dataset with batching + background prefetch.

    num_workers>0 runs the fetch loop in daemon threads feeding a bounded
    queue (the BlockingQueue analog); prefetch overlaps host work with
    device compute. Multiprocess fetch arrives with the C++ io core.
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last,
            )
            self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _fetch_iter(self):
        if self._iterable_mode:
            buf = []
            for item in self.dataset:
                buf.append(item)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not getattr(self, "drop_last", False):
                yield self.collate_fn(buf)
        else:
            for batch_idx in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in batch_idx])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._fetch_iter()
            return
        # background-thread prefetch pipeline
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor * self.num_workers)
        sentinel = object()
        error: list = []

        def producer():
            try:
                for item in self._fetch_iter():
                    q.put(item)
            except BaseException as e:  # propagate to the consumer, don't
                error.append(e)         # silently truncate the epoch
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        t.join()
        if error:
            raise error[0]
