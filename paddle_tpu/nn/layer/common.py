"""Common layers + containers + activation layers (reference surface:
python/paddle/nn/layer/common.py, container.py, activation.py — unverified,
SURVEY.md §0)."""
from __future__ import annotations

from collections import OrderedDict

from .layers import Layer, ParamAttr
from .. import functional as F
from .. import initializer as I
from ...core.tensor import Parameter, Tensor

__all__ = [
    "Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
    "Flatten", "Unflatten", "Identity", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D", "Upsample",
    "UpsamplingNearest2D", "UpsamplingBilinear2D", "PixelShuffle",
    "PixelUnshuffle", "ChannelShuffle", "CosineSimilarity", "Bilinear",
    "Unfold", "Fold",
    "Sequential", "LayerList", "LayerDict", "ParameterList",
    "ReLU", "ReLU6", "GELU", "SiLU", "Swish", "Sigmoid", "Tanh", "Softmax",
    "LogSoftmax", "LeakyReLU", "ELU", "SELU", "CELU", "Hardswish",
    "Hardsigmoid", "Hardtanh", "Hardshrink", "Softshrink", "Softplus",
    "Softsign", "Tanhshrink", "ThresholdedReLU", "Mish", "PReLU", "RReLU",
    "Maxout", "GLU", "LogSigmoid",
]


class Linear(Layer):
    """y = xW + b with paddle weight layout (in_features, out_features)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features, self._out_features = in_features, out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_features,), attr=bias_attr, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (
            padding_idx
            if padding_idx is None or padding_idx >= 0
            else num_embeddings + padding_idx
        )
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0),
        )
        if self._padding_idx is not None:
            import jax.numpy as jnp

            self.weight._value = self.weight._value.at[self._padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, self.p, self.axis, self.training, self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, self.training, self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ...tensor.extras import unflatten

        return unflatten(x, self.axis, self.shape)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class _PadN(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadN):
    pass


class Pad3D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(
            x, self.size, self.scale_factor, self.mode, self.align_corners,
            self.align_mode, self.data_format,
        )


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr
        )
        self.bias = (
            self.create_parameter((out_features,), attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


# -- containers --------------------------------------------------------------
class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, item in enumerate(layers):
                if isinstance(item, (tuple, list)) and len(item) == 2 and isinstance(item[0], str):
                    self.add_sublayer(item[0], item[1])
                else:
                    self.add_sublayer(str(i), item)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, layer in enumerate(sublayers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __setitem__(self, idx, layer):
        keys = list(self._sub_layers)
        self._sub_layers[keys[idx]] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for layer in layers:
            self.append(layer)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        if isinstance(sublayers, dict):
            sublayers = sublayers.items()
        for key, layer in sublayers:
            self.add_sublayer(key, layer)


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        keys = list(self._parameters)
        return self._parameters[keys[idx]]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


# -- activation layers -------------------------------------------------------
def _act_layer(fname, fn_kwargs=()):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._args, self._kwargs = args, kwargs

        def forward(self, x):
            return getattr(F, fname)(x, *self._args, **self._kwargs)

    _Act.__name__ = fname
    return _Act


class ReLU(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.relu(x)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=I.Constant(init),
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


ReLU6 = _act_layer("relu6")
SiLU = _act_layer("silu")
Swish = _act_layer("swish")
Sigmoid = _act_layer("sigmoid")
Tanh = _act_layer("tanh")
ELU = _act_layer("elu")
SELU = _act_layer("selu")
CELU = _act_layer("celu")
Hardswish = _act_layer("hardswish")
Hardsigmoid = _act_layer("hardsigmoid")
Hardtanh = _act_layer("hardtanh")
Hardshrink = _act_layer("hardshrink")
Softshrink = _act_layer("softshrink")
Softplus = _act_layer("softplus")
Softsign = _act_layer("softsign")
Tanhshrink = _act_layer("tanhshrink")
ThresholdedReLU = _act_layer("thresholded_relu")
Mish = _act_layer("mish")
RReLU = _act_layer("rrelu")
Maxout = _act_layer("maxout")
GLU = _act_layer("glu")
LogSigmoid = _act_layer("log_sigmoid")
