"""nn.Layer — the module base class.

Mirrors the reference's Layer (reference: python/paddle/nn/layer/layers.py —
unverified, SURVEY.md §0): parameter/sublayer registration via __setattr__,
hooks, state_dict with structured names, train/eval mode, apply/to. All
parameter storage is paddle_tpu Tensors; the functional bridge
(``paddle_tpu.jit.functional_call``) swaps their values for jit'd training.
"""
from __future__ import annotations

import re
from collections import OrderedDict

import numpy as np

from ...core.tensor import Tensor, Parameter
from ...core.dtype import get_default_dtype, to_jax_dtype
from ...core import autograd
from .. import initializer as init_mod

__all__ = ["Layer", "ParamAttr"]


class ParamAttr:
    """paddle.ParamAttr (reference: python/paddle/base/param_attr.py)."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        do_model_average=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


_layer_counters: dict[str, int] = {}


def _unique_name(prefix: str) -> str:
    idx = _layer_counters.get(prefix, 0)
    _layer_counters[prefix] = idx + 1
    return f"{prefix}_{idx}"


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks, self._key = hooks, key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        self.training = True
        self._dtype = dtype or get_default_dtype()
        self._full_name = _unique_name(
            name_scope or re.sub(r"(?<!^)(?=[A-Z])", "_", type(self).__name__).lower()
        )
        self._parameters: OrderedDict[str, Parameter] = OrderedDict()
        self._sub_layers: OrderedDict[str, Layer] = OrderedDict()
        self._buffers: OrderedDict[str, Tensor] = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: OrderedDict[int, object] = OrderedDict()
        self._forward_post_hooks: OrderedDict[int, object] = OrderedDict()
        self._hook_id = 0

    # -- registration --------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning params")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning layers")
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params.pop(name)
                object.__setattr__(self, name, None)
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            if value is None:
                buffers.pop(name)
                object.__setattr__(self, name, None)
            else:
                buffers[name] = value if isinstance(value, Tensor) else Tensor(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = (
            list(self._parameters) + list(self._sub_layers) + list(self._buffers)
        )
        return sorted(set(list(super().__dir__()) + extra))

    def add_sublayer(self, name, sublayer):
        if not isinstance(sublayer, Layer) and sublayer is not None:
            raise TypeError("sublayer must be a Layer")
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("parameter must be a Parameter")
        self._parameters[str(name)] = parameter
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[str(name)] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        """Create + register-later parameter (caller assigns it)."""
        dtype = dtype or self._dtype
        if isinstance(attr, ParamAttr):
            initializer = attr.initializer
            trainable = attr.trainable
        elif attr is False:
            return None
        else:
            initializer, trainable = None, True
        if initializer is None:
            initializer = default_initializer
        if initializer is None:
            if is_bias:
                initializer = init_mod.Constant(0.0)
            else:
                initializer = init_mod.XavierNormal()
        value = initializer(shape, to_jax_dtype(dtype))
        p = Parameter(value, dtype=dtype, trainable=trainable)
        # deterministic paddle-style name (linear_0.w_0) so optimizer
        # checkpoints keyed by name survive process restarts
        idx = self.__dict__.setdefault("_param_name_counter", 0)
        self.__dict__["_param_name_counter"] = idx + 1
        p.name = f"{self._full_name}.{'b' if is_bias else 'w'}_{idx}"
        if isinstance(attr, ParamAttr):
            p._param_attr = attr
            if attr.name:
                p.name = attr.name
            p.optimize_attr = {"learning_rate": attr.learning_rate}
            p.regularizer = attr.regularizer
            p.need_clip = attr.need_clip
        else:
            p.optimize_attr = {"learning_rate": 1.0}
            p.regularizer = None
            p.need_clip = True
        p.is_bias = is_bias
        return p

    def create_variable(self, name=None, persistable=False, dtype=None):
        import jax.numpy as jnp

        t = Tensor(jnp.zeros((), to_jax_dtype(dtype or self._dtype)))
        t.persistable = persistable
        return t

    # -- traversal -----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{name}.{bname}" if name else bname), b

    def _traverse(self, prefix="", include_sublayers=True):
        yield prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._traverse(sub_prefix, True)

    def children(self):
        for _, sub in self.named_children():
            yield sub

    def named_children(self):
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def sublayers(self, include_self=False):
        out = []
        for name, layer in self._traverse("", True):
            if layer is self and not include_self:
                continue
            out.append(layer)
        return out

    def named_sublayers(self, prefix="", include_self=False):
        for name, layer in self._traverse(prefix, True):
            if layer is self and not include_self:
                continue
            yield name, layer

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # -- mode ----------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        out = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            out[name] = p
        for name, layer in self._traverse("", include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and bname not in layer._non_persistable_buffer_names:
                    key = f"{name}.{bname}" if name else bname
                    out[key] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for key, value in state_dict.items():
            if key not in own:
                unexpected.append(key)
                continue
            target = own[key]
            v = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
            if tuple(v.shape) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {key}: loaded {v.shape} vs "
                    f"param {tuple(target.shape)}"
                )
            target.set_value(v)
        for key in own:
            if key not in state_dict:
                missing.append(key)
        return missing, unexpected

    load_dict = set_state_dict

    # -- conversion ----------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._transform_dtype(dtype)
        return self

    def astype(self, dtype):
        self._transform_dtype(dtype)
        return self

    def _transform_dtype(self, dtype):
        import jax.numpy as jnp

        jdt = to_jax_dtype(dtype)
        for _, p in self.named_parameters():
            if jnp.issubdtype(p._value.dtype, jnp.floating):
                p._value = p._value.astype(jdt)
        for _, b in self.named_buffers():
            if jnp.issubdtype(b._value.dtype, jnp.floating):
                b._value = b._value.astype(jdt)
        for layer in self.sublayers(include_self=True):
            layer._dtype = str(jdt)

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + ln for ln in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
