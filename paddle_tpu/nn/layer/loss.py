"""Loss layers (reference surface: python/paddle/nn/layer/loss.py —
unverified, SURVEY.md §0)."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F

__all__ = [
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
    "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "MarginRankingLoss",
    "CosineEmbeddingLoss", "HingeEmbeddingLoss", "TripletMarginLoss",
    "SigmoidFocalLoss", "CTCLoss", "SoftMarginLoss",
    "MultiLabelSoftMarginLoss", "MultiMarginLoss", "GaussianNLLLoss",
    "PoissonNLLLoss", "PairwiseDistance", "HSigmoidLoss",
]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self._args = dict(
            weight=weight, ignore_index=ignore_index, reduction=reduction,
            soft_label=soft_label, axis=axis, use_softmax=use_softmax,
            label_smoothing=label_smoothing,
        )

    def forward(self, input, label):
        return F.cross_entropy(input, label, **self._args)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self._args = dict(weight=weight, ignore_index=ignore_index, reduction=reduction)

    def forward(self, input, label):
        return F.nll_loss(input, label, **self._args)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight
        )


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction, self.log_target = reduction, log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(
            input1, input2, label, self.margin, self.reduction
        )


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._args = dict(margin=margin, p=p, epsilon=epsilon, swap=swap,
                          reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, **self._args)


class SigmoidFocalLoss(Layer):
    def __init__(self, alpha=0.25, gamma=2.0, reduction="sum", name=None):
        super().__init__()
        self._args = dict(alpha=alpha, gamma=gamma, reduction=reduction)

    def forward(self, logit, label, normalizer=None):
        return F.sigmoid_focal_loss(logit, label, normalizer, **self._args)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(
            input, label, self.weight, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(
            input, label, self.p, self.margin, self.weight, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(
            input, label, variance, self.full, self.epsilon, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(
            input, label, self.log_input, self.full, self.epsilon,
            self.reduction)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ...tensor.linalg import norm

        # one p-norm implementation lives in linalg.norm
        return norm(x - y + self.epsilon, p=self.p, axis=-1,
                    keepdim=self.keepdim)


class HSigmoidLoss(Layer):
    """paddle.nn.HSigmoidLoss: hierarchical sigmoid over the default
    complete binary tree (is_custom=False) or caller-supplied
    path_table/path_code (is_custom=True). Holds the (num_classes-1, D)
    node weight (num-nodes rows for custom trees are the caller's
    responsibility via num_classes)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self._feature_size = feature_size
        self._num_classes = num_classes
        self._is_custom = is_custom
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr)
        self.bias = self.create_parameter(
            (num_classes - 1,), attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        if self._is_custom and (path_table is None or path_code is None):
            raise ValueError(
                "is_custom HSigmoidLoss needs path_table and path_code")
        return F.hsigmoid_loss(
            input, label, self._num_classes, self.weight, bias=self.bias,
            path_table=path_table if self._is_custom else None,
            path_code=path_code if self._is_custom else None)
