"""Recurrent layers via lax.scan — the compiler-friendly TPU recurrence
(reference surface: python/paddle/nn/layer/rnn.py — unverified, SURVEY.md
§0). Multi-layer/bidirectional LSTM/GRU/SimpleRNN with paddle's
(outputs, final_states) contract.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .layers import Layer
from .. import initializer as I
from ...core.tensor import Tensor
from ...core.dispatch import apply
from ...tensor._helpers import ensure_tensor

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "SimpleRNN", "LSTM", "GRU", "BiRNN",
]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...tensor.creation import full

        b = batch_ref.shape[batch_dim_idx]
        state_shape = [b, self.hidden_size]
        if isinstance(self.state_shape, tuple):
            return tuple(
                full(state_shape, init_value, dtype or "float32")
                for _ in self.state_shape
            )
        return full(state_shape, init_value, dtype or "float32")


def _cell_params(layer, input_size, hidden_size, n_gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
    k = 1.0 / math.sqrt(hidden_size)
    layer.weight_ih = layer.create_parameter(
        (n_gates * hidden_size, input_size), attr=weight_ih_attr,
        default_initializer=I.Uniform(-k, k),
    )
    layer.weight_hh = layer.create_parameter(
        (n_gates * hidden_size, hidden_size), attr=weight_hh_attr,
        default_initializer=I.Uniform(-k, k),
    )
    layer.bias_ih = (
        layer.create_parameter(
            (n_gates * hidden_size,), attr=bias_ih_attr, is_bias=True,
            default_initializer=I.Uniform(-k, k),
        )
        if bias_ih_attr is not False
        else None
    )
    layer.bias_hh = (
        layer.create_parameter(
            (n_gates * hidden_size,), attr=bias_hh_attr, is_bias=True,
            default_initializer=I.Uniform(-k, k),
        )
        if bias_hh_attr is not False
        else None
    )


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        self.state_shape = (hidden_size,)
        _cell_params(self, input_size, hidden_size, 1, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def step_fn(self):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        has_bi, has_bh = self.bias_ih is not None, self.bias_hh is not None

        def step(x, h, w_ih, w_hh, b_ih, b_hh):
            z = x @ w_ih.T + h @ w_hh.T
            if has_bi:
                z = z + b_ih
            if has_bh:
                z = z + b_hh
            return act(z)

        return step

    def _param_values(self):
        return (
            self.weight_ih, self.weight_hh,
            self.bias_ih, self.bias_hh,
        )

    def forward(self, inputs, states=None):
        states = states if states is not None else self.get_initial_states(inputs)
        step = self.step_fn()
        args = [ensure_tensor(inputs), ensure_tensor(states)]
        params = [p for p in self._param_values() if p is not None]

        def fn(x, h, *ps):
            ps = list(ps)
            w_ih, w_hh = ps[0], ps[1]
            b_ih = ps[2] if self.bias_ih is not None else None
            b_hh = ps[3 if self.bias_ih is not None else 2] if self.bias_hh is not None else None
            out = step(x, h, w_ih, w_hh, b_ih, b_hh)
            return out, out

        out = apply(fn, *args, *params, op_name="simple_rnn_cell")
        return out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.state_shape = ((hidden_size,), (hidden_size,))
        _cell_params(self, input_size, hidden_size, 4, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @staticmethod
    def _compute(x, h, c, w_ih, w_hh, b_ih, b_hh, hidden_size):
        z = x @ w_ih.T + h @ w_hh.T
        if b_ih is not None:
            z = z + b_ih
        if b_hh is not None:
            z = z + b_hh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new

    def forward(self, inputs, states=None):
        states = states if states is not None else self.get_initial_states(inputs)
        h, c = states
        params = [self.weight_ih, self.weight_hh]
        nb = 0
        if self.bias_ih is not None:
            params.append(self.bias_ih)
            nb += 1
        if self.bias_hh is not None:
            params.append(self.bias_hh)

        def fn(x, hv, cv, w_ih, w_hh, *bs):
            b_ih = bs[0] if self.bias_ih is not None else None
            b_hh = bs[-1] if self.bias_hh is not None else None
            h_new, c_new = LSTMCell._compute(
                x, hv, cv, w_ih, w_hh, b_ih, b_hh, self.hidden_size
            )
            return h_new, (h_new, c_new)

        return apply(
            fn, ensure_tensor(inputs), ensure_tensor(h), ensure_tensor(c),
            *params, op_name="lstm_cell",
        )


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.state_shape = (hidden_size,)
        _cell_params(self, input_size, hidden_size, 3, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @staticmethod
    def _compute(x, h, w_ih, w_hh, b_ih, b_hh):
        gi = x @ w_ih.T
        gh = h @ w_hh.T
        if b_ih is not None:
            gi = gi + b_ih
        if b_hh is not None:
            gh = gh + b_hh
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n)
        return (1 - z) * n + z * h

    def forward(self, inputs, states=None):
        states = states if states is not None else self.get_initial_states(inputs)
        params = [self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            params.append(self.bias_ih)
        if self.bias_hh is not None:
            params.append(self.bias_hh)

        def fn(x, hv, w_ih, w_hh, *bs):
            b_ih = bs[0] if self.bias_ih is not None else None
            b_hh = bs[-1] if self.bias_hh is not None else None
            out = GRUCell._compute(x, hv, w_ih, w_hh, b_ih, b_hh)
            return out, out

        return apply(
            fn, ensure_tensor(inputs), ensure_tensor(states), *params,
            op_name="gru_cell",
        )


class RNN(Layer):
    """Runs a cell over a sequence with lax.scan."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        cell = self.cell
        if initial_states is None:
            ref = inputs if self.time_major else inputs
            b = ref.shape[1] if self.time_major else ref.shape[0]
            from ...tensor.creation import zeros

            if isinstance(cell.state_shape, tuple) and isinstance(
                cell.state_shape[0], tuple
            ):
                initial_states = tuple(
                    zeros([b, cell.hidden_size], dtype="float32")
                    for _ in cell.state_shape
                )
            else:
                initial_states = zeros([b, cell.hidden_size], dtype="float32")

        is_lstm = isinstance(cell, LSTMCell)
        params = [cell.weight_ih, cell.weight_hh]
        if cell.bias_ih is not None:
            params.append(cell.bias_ih)
        if cell.bias_hh is not None:
            params.append(cell.bias_hh)
        has_bi = cell.bias_ih is not None
        has_bh = cell.bias_hh is not None
        time_major, is_reverse = self.time_major, self.is_reverse
        if is_lstm:
            state_args = [ensure_tensor(initial_states[0]), ensure_tensor(initial_states[1])]
        else:
            state_args = [ensure_tensor(initial_states)]

        cell_type = type(cell)

        def fn(x, *rest):
            n_states = 2 if is_lstm else 1
            states = rest[:n_states]
            ps = rest[n_states:]
            w_ih, w_hh = ps[0], ps[1]
            b_ih = ps[2] if has_bi else None
            b_hh = ps[2 + (1 if has_bi else 0)] if has_bh else None
            seq = x if time_major else jnp.swapaxes(x, 0, 1)
            if is_reverse:
                seq = jnp.flip(seq, 0)

            if is_lstm:
                def step(carry, xt):
                    h, c = carry
                    h2, c2 = LSTMCell._compute(xt, h, c, w_ih, w_hh, b_ih, b_hh, cell.hidden_size)
                    return (h2, c2), h2

                carry, outs = jax.lax.scan(step, (states[0], states[1]), seq)
                final = carry
            elif cell_type is GRUCell:
                def step(h, xt):
                    h2 = GRUCell._compute(xt, h, w_ih, w_hh, b_ih, b_hh)
                    return h2, h2

                final, outs = jax.lax.scan(step, states[0], seq)
                final = (final,)
            else:
                act = jnp.tanh if getattr(cell, "activation", "tanh") == "tanh" else jax.nn.relu

                def step(h, xt):
                    z = xt @ w_ih.T + h @ w_hh.T
                    if b_ih is not None:
                        z = z + b_ih
                    if b_hh is not None:
                        z = z + b_hh
                    h2 = act(z)
                    return h2, h2

                final, outs = jax.lax.scan(step, states[0], seq)
                final = (final,)
            if is_reverse:
                outs = jnp.flip(outs, 0)
            if not time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            return (outs, *final)

        result = apply(fn, ensure_tensor(inputs), *state_args, *params, op_name="rnn")
        outs = result[0]
        if is_lstm:
            return outs, (result[1], result[2])
        return outs, result[1]


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat

        states_fw = states_bw = None
        if initial_states is not None:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode, self.num_layers = mode, num_layers
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        self.hidden_size = hidden_size

        def make_cell(isz):
            if mode == "LSTM":
                return LSTMCell(isz, hidden_size, weight_ih_attr,
                                weight_hh_attr, bias_ih_attr, bias_hh_attr)
            if mode == "GRU":
                return GRUCell(isz, hidden_size, weight_ih_attr,
                               weight_hh_attr, bias_ih_attr, bias_hh_attr)
            return SimpleRNNCell(isz, hidden_size, activation, weight_ih_attr,
                                 weight_hh_attr, bias_ih_attr, bias_hh_attr)

        from .common import LayerList

        self.rnns = LayerList()
        for layer_i in range(num_layers):
            isz = input_size if layer_i == 0 else hidden_size * self.num_directions
            if bidirect:
                self.rnns.append(BiRNN(make_cell(isz), make_cell(isz), time_major))
            else:
                self.rnns.append(RNN(make_cell(isz), False, time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import functional as F
        from ...tensor.manipulation import stack

        out = inputs
        finals = []
        for i, rnn in enumerate(self.rnns):
            out, st = rnn(out, None, sequence_length)
            finals.append(st)
            if self.dropout > 0 and i < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        # assemble final states in paddle layout (num_layers*dirs, B, H)
        if self.mode == "LSTM":
            if self.num_directions == 1:
                h = stack([st[0] for st in finals], axis=0)
                c = stack([st[1] for st in finals], axis=0)
            else:
                hs, cs = [], []
                for st_fw, st_bw in finals:
                    hs += [st_fw[0], st_bw[0]]
                    cs += [st_fw[1], st_bw[1]]
                h, c = stack(hs, axis=0), stack(cs, axis=0)
            return out, (h, c)
        if self.num_directions == 1:
            h = stack(finals, axis=0)
        else:
            hs = []
            for st_fw, st_bw in finals:
                hs += [st_fw, st_bw]
            h = stack(hs, axis=0)
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)
