"""Norm + pooling layers (reference surface: python/paddle/nn/layer/norm.py,
pooling.py — unverified, SURVEY.md §0)."""
from __future__ import annotations

import jax.numpy as jnp

from .layers import Layer
from .. import functional as F
from .. import initializer as I
from ...core.tensor import Tensor

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "RMSNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm",
    "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
    "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D", "AdaptiveMaxPool3D",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = (
            self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
            if weight_attr is not False
            else None
        )
        self.bias = (
            self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm(num_channels) API."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=None, **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under GSPMD the batch axis is sharded and XLA
    computes global batch statistics automatically when the reduction spans
    the full array, so SyncBatchNorm == BatchNorm here (the reference needs
    explicit NCCL allreduce of stats; reference:
    python/paddle/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = (
            self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
            if weight_attr is not False
            else None
        )
        self.bias = (
            self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True
            )
            if bias_attr is not False
            else None
        )

    def forward(self, x):
        return F.layer_norm(
            x, self._normalized_shape, self.weight, self.bias, self._epsilon
        )

    def extra_repr(self):
        return f"normalized_shape={list(self._normalized_shape)}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """The Llama-family norm; routes to the Pallas kernel on TPU."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr,
            default_initializer=I.Constant(1.0),
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups, self._epsilon = num_groups, epsilon
        self._data_format = data_format
        self.weight = (
            self.create_parameter(
                (num_channels,), attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
            if weight_attr is not False
            else None
        )
        self.bias = (
            self.create_parameter((num_channels,), attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )

    def forward(self, x):
        return F.group_norm(
            x, self._num_groups, self._epsilon, self.weight, self.bias,
            self._data_format,
        )


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True
            )
        else:
            self.weight = self.bias = None

    def forward(self, x):
        return F.instance_norm(
            x, weight=self.weight, bias=self.bias, eps=self._epsilon,
            data_format=self._data_format,
        )


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self._args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight (reference:
    python/paddle/nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim, self._power_iters, self._epsilon = dim, power_iters, epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            (h,), default_initializer=I.Normal(0.0, 1.0)
        )
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            (w,), default_initializer=I.Normal(0.0, 1.0)
        )
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.dispatch import apply
        import jax

        dim, eps, iters = self._dim, self._epsilon, self._power_iters
        u0, v0 = self.weight_u._value, self.weight_v._value

        def fn(w):
            mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma

        return apply(fn, weight, op_name="spectral_norm")


# -- pooling layers ----------------------------------------------------------
def _pool_layer(fname, n, data_format_default):
    class _Pool(Layer):
        def __init__(self, kernel_size=None, stride=None, padding=0, **kwargs):
            super().__init__()
            self._kernel_size = kernel_size
            self._stride = stride
            self._padding = padding
            self._kwargs = {
                k: v for k, v in kwargs.items() if k not in ("name",)
            }

        def forward(self, x):
            return getattr(F, fname)(
                x, self._kernel_size, self._stride, self._padding, **self._kwargs
            )

    _Pool.__name__ = fname
    return _Pool


MaxPool1D = _pool_layer("max_pool1d", 1, "NCL")
MaxPool2D = _pool_layer("max_pool2d", 2, "NCHW")
MaxPool3D = _pool_layer("max_pool3d", 3, "NCDHW")
AvgPool1D = _pool_layer("avg_pool1d", 1, "NCL")
AvgPool2D = _pool_layer("avg_pool2d", 2, "NCHW")
AvgPool3D = _pool_layer("avg_pool3d", 3, "NCDHW")


def _adaptive_pool_layer(fname):
    class _Pool(Layer):
        def __init__(self, output_size, **kwargs):
            super().__init__()
            self._output_size = output_size
            self._kwargs = {k: v for k, v in kwargs.items() if k != "name"}

        def forward(self, x):
            return getattr(F, fname)(x, self._output_size, **self._kwargs)

    _Pool.__name__ = fname
    return _Pool


AdaptiveAvgPool1D = _adaptive_pool_layer("adaptive_avg_pool1d")
AdaptiveAvgPool2D = _adaptive_pool_layer("adaptive_avg_pool2d")
AdaptiveAvgPool3D = _adaptive_pool_layer("adaptive_avg_pool3d")
AdaptiveMaxPool1D = _adaptive_pool_layer("adaptive_max_pool1d")
AdaptiveMaxPool2D = _adaptive_pool_layer("adaptive_max_pool2d")
AdaptiveMaxPool3D = _adaptive_pool_layer("adaptive_max_pool3d")
