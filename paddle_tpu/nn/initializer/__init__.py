"""Weight initializers (reference surface: python/paddle/nn/initializer/ —
unverified, SURVEY.md §0). Each initializer is a callable
``(shape, jax_dtype) -> jax array`` drawing from the global generator.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.random import next_key

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
    "set_global_initializer",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


def _fans(shape):
    shape = tuple(int(s) for s in shape)
    if len(shape) < 2:
        fan_in = fan_out = int(np.prod(shape)) if shape else 1
    else:
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
        fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
        if len(shape) == 2:
            fan_in, fan_out = shape[0], shape[1]
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(tuple(int(s) for s in shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        sample_dt = dtype if jnp.issubdtype(dtype, jnp.floating) else jnp.float32
        return self.mean + self.std * jax.random.normal(
            next_key(), tuple(int(s) for s in shape), sample_dt
        )


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=jnp.float32):
        z = jax.random.truncated_normal(
            next_key(), self.a, self.b, tuple(int(s) for s in shape), jnp.float32
        )
        return (self.mean + self.std * z).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(
            next_key(), tuple(int(s) for s in shape), jnp.float32,
            minval=self.low, maxval=self.high,
        ).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (
            std * jax.random.normal(next_key(), tuple(int(s) for s in shape), jnp.float32)
        ).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(
            next_key(), tuple(int(s) for s in shape), jnp.float32,
            minval=-limit, maxval=limit,
        ).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (
            std * jax.random.normal(next_key(), tuple(int(s) for s in shape), jnp.float32)
        ).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(
            next_key(), tuple(int(s) for s in shape), jnp.float32,
            minval=-limit, maxval=limit,
        ).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        from ...core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), dtype)
        if tuple(arr.shape) != tuple(int(s) for s in shape):
            arr = arr.reshape(tuple(int(s) for s in shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        shape = tuple(int(s) for s in shape)
        rows, cols = shape[0], int(np.prod(shape[1:]))
        n = max(rows, cols)
        a = jax.random.normal(next_key(), (n, n), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        shape = tuple(int(s) for s in shape)
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        centers = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic)):
            out[(i, i) + centers] = 1.0
        return jnp.asarray(out, dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init
