"""paddle.nn namespace (reference: python/paddle/nn/ — unverified,
SURVEY.md §0)."""
from . import initializer  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer.layers import Layer, ParamAttr  # noqa: F401
from . import functional  # noqa: F401
from . import functional as F  # noqa: F401

# grad-clip classes live under paddle.nn in the reference
from ..optimizer.clip import (  # noqa: F401
    ClipGradByValue,
    ClipGradByNorm,
    ClipGradByGlobalNorm,
)
from . import quant  # noqa: E402,F401
