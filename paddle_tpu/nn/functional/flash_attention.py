"""paddle.nn.functional.flash_attention submodule parity (the reference
exposes flash attention under this path too)."""
from .attention import (  # noqa: F401
    scaled_dot_product_attention,
    flash_attention,
    flash_attn_unpadded,
)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         fixed_seed_offset=None, rng_name="", training=True,
                         name=None):
    """Packed QKV variant: qkv is (B, S, 3, H, D)."""
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)

