"""paddle.nn.functional.flash_attention submodule parity (the reference
exposes flash attention under this path too)."""
from .attention import (  # noqa: F401
    scaled_dot_product_attention,
    flash_attention,
    flash_attn_unpadded,
)

flash_attn_qkvpacked = None  # packed variants land with the decode stack
