"""Attention functionals.

``scaled_dot_product_attention`` mirrors paddle's API (reference:
python/paddle/nn/functional/flash_attention.py — unverified, SURVEY.md §0)
and routes to the Pallas flash-attention kernel on TPU (the analog of the
reference's vendored flash-attn CUDA kernel), falling back to a fused XLA
softmax-attention elsewhere.
"""
from __future__ import annotations

import math
import warnings

import jax
import jax.numpy as jnp

from ...tensor._helpers import Tensor, apply, ensure_tensor
from ...core.flags import get_flags

# Imported eagerly so a broken kernel package fails loudly at import time
# instead of silently falling back at every call (round-1 advisor finding).
from ...ops.pallas.flash_attention import flash_attention as _pallas_flash
from ...ops.pallas.varlen_flash_attention import (
    varlen_flash_attention as _pallas_varlen_flash,
)


def _xla_attention(q, k, v, mask=None, causal=False, dropout_p=0.0, scale=None,
                   key=None):
    """Reference attention in pure XLA ops; layout (B, S, H, D)."""
    if k.shape[2] != q.shape[2]:  # GQA/MQA: repeat kv heads to q heads
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    # (B, H, Sq, Sk)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * sc
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def sliding_window_attention(query, key, value, window_size,
                             training=True, name=None):
    """Causal sliding-window attention (Mistral semantics: each query
    attends to the last ``window_size`` keys, itself included). Routes
    to the Pallas flash kernel's banded tiles on TPU (cost
    O(S * window)); elsewhere an XLA banded-mask fallback."""
    query, key_, value = (ensure_tensor(query), ensure_tensor(key),
                          ensure_tensor(value))
    w = int(window_size)
    if w < 1:
        # validated HERE: the kernel's own ValueError would be swallowed
        # by the capability-fallback except below, and the XLA path's
        # empty band would softmax to NaN
        raise ValueError(f"window_size must be >= 1, got {window_size}")
    flags = get_flags(["FLAGS_use_pallas_kernels", "FLAGS_pallas_force"])
    use_pallas = (
        flags["FLAGS_use_pallas_kernels"]
        and (jax.default_backend() == "tpu" or flags["FLAGS_pallas_force"])
        and query._value.shape[-1] >= 64
    )
    if use_pallas:
        try:
            return apply(
                lambda q, k, v: _pallas_flash(q, k, v, causal=True,
                                              window_size=w),
                query, key_, value, op_name="sliding_window_attention",
            )
        except ValueError as e:
            warnings.warn(
                f"Pallas sliding-window attention fell back to XLA: {e}",
                RuntimeWarning)

    def fn(q, k, v):
        sq, sk = q.shape[1], k.shape[1]
        qpos = jnp.arange(sq)[:, None] + (sk - sq)
        kpos = jnp.arange(sk)[None, :]
        band = (kpos <= qpos) & (kpos >= qpos - w + 1)
        return _xla_attention(q, k, v, mask=band[None, None], causal=False,
                              dropout_p=0.0, key=None)

    return apply(fn, query, key_, value,
                 op_name="sliding_window_attention")


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Layout (batch, seq, num_heads, head_dim) — paddle's flash-attn layout."""
    query, key_, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    flags = get_flags(["FLAGS_use_pallas_kernels", "FLAGS_pallas_force"])
    use_pallas = (
        flags["FLAGS_use_pallas_kernels"]
        and attn_mask is None
        and (dropout_p == 0.0 or not training)
        and (jax.default_backend() == "tpu" or flags["FLAGS_pallas_force"])
        and query._value.shape[-1] >= 64
    )
    if use_pallas:
        try:
            return apply(
                lambda q, k, v: _pallas_flash(q, k, v, causal=is_causal),
                query, key_, value, op_name="flash_attention",
            )
        except ValueError as e:
            # unsupported head config (e.g. H % HK != 0) — fall back, loudly
            warnings.warn(
                f"Pallas flash attention fell back to XLA: {e}", RuntimeWarning
            )

    rng_key = None
    if dropout_p > 0.0 and training:
        from ...core.random import next_key

        rng_key = next_key()

    def fn(q, k, v, *maybe_mask):
        m = maybe_mask[0] if maybe_mask else None
        return _xla_attention(
            q, k, v, mask=m, causal=is_causal,
            dropout_p=dropout_p if training else 0.0, key=rng_key,
        )

    args = [query, key_, value]
    if attn_mask is not None:
        args.append(ensure_tensor(attn_mask))
    return apply(fn, *args, op_name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal, training
    )
    if return_softmax:
        return out, None
    return out, None


def _xla_varlen_attention(q, k, v, cu_q, cu_k, scale, causal,
                          dropout_p=0.0, key=None, window=None):
    """Segment-masked XLA reference for packed varlen attention (O(T^2)
    memory) — the numeric oracle for the Pallas kernel and the off-TPU /
    dropout path. Supports GQA and unequal q/kv lengths (bottom-right
    causal)."""
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    pos_q = jnp.arange(q.shape[0])
    pos_k = jnp.arange(k.shape[0])
    seg_q = jnp.searchsorted(cu_q[1:], pos_q, side="right")
    seg_k = jnp.searchsorted(cu_k[1:], pos_k, side="right")
    logits = jnp.einsum(
        "qhd,khd->hqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    mask = seg_q[:, None] == seg_k[None, :]
    if causal:
        # bottom-right alignment per segment (kv coordinates)
        lq = cu_q[seg_q + 1] - cu_q[seg_q]
        lk = cu_k[seg_q + 1] - cu_k[seg_q]
        rel_q = pos_q - cu_q[seg_q] + lk - lq
        rel_k = pos_k - cu_k[seg_k]
        mask = mask & (rel_q[:, None] >= rel_k[None, :])
        if window is not None:
            mask = mask & (rel_k[None, :] > rel_q[:, None] - window)
    logits = jnp.where(mask[None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    # fully-masked rows (empty segments) produce nan; zero them
    probs = jnp.where(jnp.isnan(probs), 0.0, probs)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("hqk,khd->qhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        window_size=None, name=None):
    """Varlen flash attention: (total_tokens, H, D) + cumulative seqlens.

    On TPU this runs the blockwise Pallas varlen kernel
    (`ops/pallas/varlen_flash_attention.py`): per-q-block kv-block
    skipping from the segment bounds, O(sum len_i^2) compute and O(T)
    memory. Off-TPU it falls back to segment-masked XLA attention.
    """
    query, key_, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    cu_q = ensure_tensor(cu_seqlens_q)
    cu_k = ensure_tensor(cu_seqlens_k)
    # validated HERE so the Pallas and XLA backends agree (the XLA
    # band mask is nested under causal and would silently ignore it)
    if window_size is not None:
        if not causal:
            raise ValueError(
                "flash_attn_unpadded: window_size requires causal=True")
        if window_size < 1:
            raise ValueError(
                f"flash_attn_unpadded: window_size must be >= 1, got "
                f"{window_size}")

    flags = get_flags(["FLAGS_use_pallas_kernels", "FLAGS_pallas_force"])
    use_pallas = (
        flags["FLAGS_use_pallas_kernels"]
        and (dropout == 0.0 or not training)
        and (jax.default_backend() == "tpu" or flags["FLAGS_pallas_force"])
    )
    if use_pallas:
        out = apply(
            lambda q, k, v, cq, ck: _pallas_varlen_flash(
                q, k, v, cq, ck, causal=causal, sm_scale=scale,
                window_size=window_size),
            query, key_, value, cu_q, cu_k, op_name="flash_attn_unpadded",
        )
        return out, None

    rng_key = None
    if dropout > 0.0 and training:
        from ...core.random import next_key

        rng_key = next_key()
    out = apply(
        lambda q, k, v, cq, ck: _xla_varlen_attention(
            q, k, v, cq, ck, scale, causal,
            dropout_p=dropout if training else 0.0, key=rng_key,
            window=window_size),
        query, key_, value, cu_q, cu_k, op_name="flash_attn_unpadded",
    )
    return out, None


__all__ = [
    "scaled_dot_product_attention", "flash_attention", "flash_attn_unpadded",
    "sliding_window_attention",
]
