"""Convolution functionals via lax.conv_general_dilated — the direct MXU
path on TPU (reference surface: python/paddle/nn/functional/conv.py —
unverified, SURVEY.md §0). Weight layout matches paddle: OIHW (out_ch,
in_ch/groups, *spatial).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helpers import Tensor, apply, ensure_tensor


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(i) for i in v)
    return v if len(v) == n else tuple(v[i % len(v)] for i in range(n))


def _padding_arg(padding, n, strides=None):
    """paddle padding: int | list | 'SAME' | 'VALID' → lax padding config."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # full-rank [[0,0],[0,0],[top,bottom],[left,right]]
        spatial = [tuple(p) for p in padding[-n:]]
        return spatial
    raise ValueError(f"unsupported padding spec {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, n):
    strides = _tuplize(stride, n)
    dilations = _tuplize(dilation, n)
    pad = _padding_arg(padding, n, strides)
    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + "DHW"[3 - n :]
        spatial = "DHW"[3 - n :]
        dn = (f"NC{spatial}", f"OI{spatial}", f"NC{spatial}")
    else:
        spatial = "DHW"[3 - n :]
        dn = (f"N{spatial}C", f"OI{spatial}", f"N{spatial}C")

    def fn(v, w, *maybe_b):
        out = jax.lax.conv_general_dilated(
            v,
            w,
            window_strides=strides,
            padding=pad,
            rhs_dilation=dilations,
            dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=(
                jnp.float32 if v.dtype in (jnp.bfloat16, jnp.float16) else None
            ),
        )
        out = out.astype(v.dtype)
        if maybe_b:
            b = maybe_b[0]
            if data_format.startswith("NC"):
                b = b.reshape((1, -1) + (1,) * n)
            else:
                b = b.reshape((1,) + (1,) * n + (-1,))
            out = out + b.astype(out.dtype)
        return out

    args = [ensure_tensor(x), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply(fn, *args, op_name=f"conv{n}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, data_format, n, output_size=None):
    strides = _tuplize(stride, n)
    dilations = _tuplize(dilation, n)
    pad = _padding_arg(padding, n, strides)
    opad = _tuplize(output_padding, n)
    spatial = "DHW"[3 - n :]
    if data_format.startswith("NC"):
        dn = (f"NC{spatial}", f"IO{spatial}", f"NC{spatial}")
    else:
        dn = (f"N{spatial}C", f"IO{spatial}", f"N{spatial}C")

    def fn(v, w, *maybe_b):
        if isinstance(pad, str):
            padding_cfg = pad
        else:
            # conv_transpose padding: lax.conv_transpose handles the
            # transpose-of-padding arithmetic when given explicit config
            k = [
                (w.shape[2 + i] - 1) * dilations[i] for i in range(n)
            ]
            padding_cfg = [
                (k[i] - pad[i][0], k[i] - pad[i][1] + opad[i]) for i in range(n)
            ]
        if groups > 1:
            # grouped transpose conv: split along channel groups
            vs = jnp.split(v, groups, axis=1 if data_format.startswith("NC") else -1)
            ws = jnp.split(w, groups, axis=0)
            outs = [
                jax.lax.conv_general_dilated(
                    vg, wg,
                    window_strides=(1,) * n,
                    padding=padding_cfg,
                    lhs_dilation=strides,
                    rhs_dilation=dilations,
                    dimension_numbers=dn,
                )
                for vg, wg in zip(vs, ws)
            ]
            out = jnp.concatenate(outs, axis=1 if data_format.startswith("NC") else -1)
        else:
            out = jax.lax.conv_general_dilated(
                v, w,
                window_strides=(1,) * n,
                padding=padding_cfg,
                lhs_dilation=strides,
                rhs_dilation=dilations,
                dimension_numbers=dn,
            )
        if maybe_b:
            b = maybe_b[0]
            if data_format.startswith("NC"):
                b = b.reshape((1, -1) + (1,) * n)
            else:
                b = b.reshape((1,) + (1,) * n + (-1,))
            out = out + b.astype(out.dtype)
        return out

    args = [ensure_tensor(x), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply(fn, *args, op_name=f"conv{n}d_transpose")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 1, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 2, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 3, output_size)


__all__ = [
    "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
]
