"""Common functionals: linear/embedding/dropout/interpolate/... (reference
surface: python/paddle/nn/functional/common.py, input.py — unverified,
SURVEY.md §0)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helpers import Tensor, apply, ensure_tensor
from ...tensor.manipulation import pad, unfold  # re-export paddle F.pad  # noqa: F401
from ...core.random import next_key


def linear(x, weight, bias=None, name=None):
    """paddle weight layout: (in_features, out_features) — x @ W + b."""

    def fn(v, w, *maybe_b):
        pet = jnp.float32 if v.dtype in (jnp.bfloat16, jnp.float16) else None
        out = jnp.matmul(v, w, preferred_element_type=pet)
        if pet is not None:
            out = out.astype(v.dtype)
        if maybe_b:
            out = out + maybe_b[0].astype(out.dtype)
        return out

    args = [ensure_tensor(x), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply(fn, *args, op_name="linear")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def fn(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return apply(fn, x, weight, op_name="embedding")


def one_hot(x, num_classes, name=None):
    from ...tensor.creation import one_hot as _oh

    return _oh(x, num_classes)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda v: v * (1.0 - p), x, op_name="dropout_infer")
        return x
    if p == 1.0:
        return apply(lambda v: jnp.zeros_like(v), x, op_name="dropout")
    key = next_key()

    def fn(v):
        shape = list(v.shape)
        if axis is not None:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)

    return apply(fn, x, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = (2, 3) if data_format == "NCHW" else (1, 2)
    inv = tuple(i for i in range(4) if i not in ax)
    # drop whole channels: mask broadcast over spatial dims
    return dropout(x, p, axis=inv, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    inv = tuple(i for i in range(5) if i not in ax)
    return dropout(x, p, axis=inv, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = next_key()

    def fn(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / ((1 - p) * (1 + p * alpha_p**2)) ** 0.5)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return apply(fn, x, op_name="alpha_dropout")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return apply(
        lambda a, b: jnp.sum(a * b, axis=axis)
        / jnp.maximum(
            jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis), eps
        ),
        ensure_tensor(x1),
        ensure_tensor(x2),
        op_name="cosine_similarity",
    )


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))

    return apply(fn, x, op_name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    r = downscale_factor

    def fn(v):
        n, c, h, w = v.shape
        v = v.reshape(n, c, h // r, r, w // r, r)
        v = v.transpose(0, 1, 3, 5, 2, 4)
        return v.reshape(n, c * r * r, h // r, w // r)

    return apply(fn, x, op_name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def fn(v):
        n, c, h, w = v.shape
        v = v.reshape(n, groups, c // groups, h, w)
        return v.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)

    return apply(fn, x, op_name="channel_shuffle")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channels_last = not data_format.startswith("NC")
    n_spatial = x.ndim - 2
    in_spatial = (
        x.shape[1:-1] if channels_last else x.shape[2:]
    )
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_spatial = tuple(int(s) for s in (size if isinstance(size, (list, tuple)) else [size]))
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * n_spatial
        out_spatial = tuple(int(d * f) for d, f in zip(in_spatial, sf))

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fn(v):
        if channels_last:
            out_shape = (v.shape[0],) + out_spatial + (v.shape[-1],)
        else:
            out_shape = v.shape[:2] + out_spatial
        if mode == "nearest":
            # paddle nearest uses floor(i * scale) source indexing
            idx = []
            for d in range(n_spatial):
                axis_len = in_spatial[d]
                out_len = out_spatial[d]
                scale = axis_len / out_len
                ii = jnp.floor(jnp.arange(out_len) * scale).astype(jnp.int32)
                idx.append(jnp.clip(ii, 0, axis_len - 1))
            out = v
            for d in range(n_spatial):
                ax = (1 if channels_last else 2) + d
                out = jnp.take(out, idx[d], axis=ax)
            return out
        if align_corners:
            # jax.image has no align_corners; do explicit linear gather
            out = v
            for d in range(n_spatial):
                ax = (1 if channels_last else 2) + d
                in_len, out_len = in_spatial[d], out_spatial[d]
                if out_len == 1 or in_len == 1:
                    pos = jnp.zeros((out_len,), jnp.float32)
                else:
                    pos = jnp.arange(out_len) * (in_len - 1) / (out_len - 1)
                lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, in_len - 1)
                hi = jnp.clip(lo + 1, 0, in_len - 1)
                t = (pos - lo).astype(v.dtype)
                shape = [1] * out.ndim
                shape[ax] = -1
                out = jnp.take(out, lo, axis=ax) * (1 - t.reshape(shape)) + jnp.take(
                    out, hi, axis=ax
                ) * t.reshape(shape)
            return out
        return jax.image.resize(v, out_shape, method=jmode).astype(v.dtype)

    return apply(fn, x, op_name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)

    def fn(y, *maybe_p):
        k = y.shape[-1]
        if maybe_p:
            return (1 - epsilon) * y + epsilon * maybe_p[0]
        return (1 - epsilon) * y + epsilon / k

    args = [label]
    if prior_dist is not None:
        args.append(ensure_tensor(prior_dist))
    return apply(fn, *args, op_name="label_smooth")


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *maybe_b):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if maybe_b:
            out = out + maybe_b[0]
        return out

    args = [ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply(fn, *args, op_name="bilinear")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im — inverse of unfold."""
    x = ensure_tensor(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    if isinstance(paddings, int):
        pt = pb = pl_ = pr = paddings
    elif len(paddings) == 2:
        pt = pb = paddings[0]
        pl_ = pr = paddings[1]
    else:
        pt, pl_, pb, pr = paddings

    def fn(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        out_h = (oh + pt + pb - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (ow + pl_ + pr - (dw * (kw - 1) + 1)) // sw + 1
        cols = v.reshape(n, c, kh, kw, out_h, out_w)
        out = jnp.zeros((n, c, oh + pt + pb, ow + pl_ + pr), v.dtype)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[
                    :, :, hi : hi + out_h * sh : sh, wj : wj + out_w * sw : sw
                ].add(cols[:, :, i, j])
        return out[:, :, pt : pt + oh, pl_ : pl_ + ow]

    return apply(fn, x, op_name="fold")


def class_center_sample(label, num_classes, num_samples, group=None):
    """paddle.nn.functional.class_center_sample (PLSC margin-softmax
    helper): keep every positive class, top up with uniformly sampled
    negatives to ``num_samples``, and remap labels into the sampled
    index space. Returns (remapped_label, sampled_class_center).

    Output size is data-dependent (|positives| may exceed num_samples),
    so this is an EAGER op — the margin-softmax training loop calls it
    on host-side label batches, like the reference's GPU op driven from
    the python layer."""
    import numpy as np

    label = ensure_tensor(label)
    if isinstance(label._value, jax.core.Tracer):
        raise ValueError(
            "class_center_sample has data-dependent output shapes and "
            "cannot run under jit tracing; call it eagerly on the label "
            "batch")
    lab = np.asarray(label._value).reshape(-1)
    pos = np.unique(lab)
    if pos.size >= num_samples:
        sampled = pos
    else:
        from ...core.random import next_key

        neg_pool = np.setdiff1d(np.arange(num_classes), pos,
                                assume_unique=True)
        k = int(jax.random.key_data(next_key())[-1])
        perm = np.random.RandomState(k % (2 ** 31)).permutation(neg_pool)
        sampled = np.concatenate(
            [pos, perm[: num_samples - pos.size]])
    remap = np.full((num_classes,), -1, np.int64)
    remap[sampled] = np.arange(sampled.size)
    from ...core.tensor import Tensor

    return (Tensor(jnp.asarray(remap[lab].reshape(label.shape))),
            Tensor(jnp.asarray(sampled)))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def fn(v):
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        left = jnp.concatenate(
            [v[:, 1:, :fold_c], jnp.zeros_like(v[:, :1, :fold_c])], axis=1
        )
        right = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold_c : 2 * fold_c]), v[:, :-1, fold_c : 2 * fold_c]],
            axis=1,
        )
        out = jnp.concatenate([left, right, v[:, :, 2 * fold_c :]], axis=2)
        return out.reshape(nt, c, h, w)

    return apply(fn, x, op_name="temporal_shift")


__all__ = [
    "linear", "embedding", "one_hot", "dropout", "dropout2d", "dropout3d",
    "alpha_dropout", "cosine_similarity", "pixel_shuffle", "pixel_unshuffle",
    "channel_shuffle", "interpolate", "upsample", "label_smooth", "bilinear",
    "pad", "unfold", "fold", "temporal_shift", "class_center_sample",
    "affine_grid", "grid_sample",
]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2-D affine sampling grid (reference paddle.nn.functional.
    affine_grid): theta (N, 2, 3) → grid (N, H, W, 2) in [-1, 1]."""
    theta = ensure_tensor(theta)
    if isinstance(out_shape, Tensor):
        out_shape = out_shape.tolist()
    n, c, h, w = [int(s) for s in out_shape]

    def fn(th):
        def axis_coords(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size)
            step = 2.0 / size
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

        ys = axis_coords(h)
        xs = axis_coords(w)
        gx, gy = jnp.meshgrid(xs, ys)  # (H, W)
        base = jnp.stack(
            [gx, gy, jnp.ones_like(gx)], axis=-1)  # (H, W, 3)
        # (N, 2, 3) @ (H*W, 3)^T → (N, H*W, 2)
        out = jnp.einsum("nij,hwj->nhwi", th.astype(jnp.float32), base)
        return out

    return apply(fn, theta, op_name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample NCHW features at normalized grid locations (reference
    paddle.nn.functional.grid_sample); differentiable through the
    gathers."""
    x = ensure_tensor(x)
    grid = ensure_tensor(grid)
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unsupported mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")

    def fn(feat, g):
        n, c, h, w = feat.shape
        gx = g[..., 0].astype(jnp.float32)  # (N, Hg, Wg)
        gy = g[..., 1].astype(jnp.float32)
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def reflect(v, lo, hi):
            # triangular-wave reflection into [lo, hi]; in-range values
            # are fixed points
            rng = hi - lo
            if rng <= 0:
                return jnp.zeros_like(v)
            return rng - jnp.abs((v - lo) % (2 * rng) - rng) + lo

        if padding_mode == "reflection":
            if align_corners:  # reflect about pixel centers
                fx = reflect(fx, 0.0, float(w - 1))
                fy = reflect(fy, 0.0, float(h - 1))
            else:  # reference reflects about pixel boundaries
                fx = reflect(fx, -0.5, float(w) - 0.5)
                fy = reflect(fy, -0.5, float(h) - 0.5)

        def gather(iy, ix):
            iyc = jnp.clip(iy, 0, h - 1)
            ixc = jnp.clip(ix, 0, w - 1)
            out = jnp.take_along_axis(
                jnp.take_along_axis(
                    feat[:, :, :, None, :],  # (N,C,H,1,W)
                    iyc[:, None, None, :, :].astype(jnp.int32).reshape(
                        n, 1, 1, -1, 1), axis=2,  # size-1 C broadcasts
                ).squeeze(2),  # (N,C,Hg*Wg,W)
                ixc[:, None, :, :].astype(jnp.int32).reshape(
                    n, 1, -1, 1), axis=3,
            )[..., 0]  # (N, C, Hg*Wg)
            valid = ((iy >= 0) & (iy <= h - 1)
                     & (ix >= 0) & (ix <= w - 1))
            if padding_mode == "zeros":
                out = out * valid.reshape(n, 1, -1)
            return out

        hw = fx.shape[1] * fx.shape[2]
        if mode == "nearest":
            out = gather(jnp.round(fy), jnp.round(fx))
        else:
            x0 = jnp.floor(fx)
            y0 = jnp.floor(fy)
            wx = fx - x0
            wy = fy - y0
            v00 = gather(y0, x0)
            v01 = gather(y0, x0 + 1)
            v10 = gather(y0 + 1, x0)
            v11 = gather(y0 + 1, x0 + 1)
            wxf = wx.reshape(n, 1, hw)
            wyf = wy.reshape(n, 1, hw)
            out = ((1 - wyf) * ((1 - wxf) * v00 + wxf * v01)
                   + wyf * ((1 - wxf) * v10 + wxf * v11))
        return out.reshape(n, c, fx.shape[1], fx.shape[2]).astype(feat.dtype)

    return apply(fn, x, grid, op_name="grid_sample")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """paddle.nn.functional.pairwise_distance: p-norm of (x - y + eps)."""
    def fn(a, b):
        d = jnp.abs(a - b + epsilon)
        if jnp.isinf(p):
            out = jnp.max(d, axis=-1, keepdims=keepdim)
        else:
            out = jnp.power(jnp.sum(jnp.power(d, p), axis=-1,
                                    keepdims=keepdim), 1.0 / p)
        return out

    return apply(fn, ensure_tensor(x), ensure_tensor(y),
                 op_name="pairwise_distance")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """paddle.nn.functional.sequence_mask: lengths → (…, maxlen) mask."""
    from ...core.dtype import to_jax_dtype

    x = ensure_tensor(x)
    if maxlen is None:
        if isinstance(x._value, jax.core.Tracer):
            raise ValueError(
                "sequence_mask(maxlen=None) needs the max length as a "
                "host value, which is unavailable while tracing "
                "(to_static/jit). Pass an explicit maxlen."
            )
        maxlen = int(jnp.max(x._value)) if x._value.size else 0
    jdt = to_jax_dtype(dtype)

    def fn(v):
        pos = jnp.arange(int(maxlen), dtype=v.dtype)
        return (pos < v[..., None]).astype(jdt)

    return apply(fn, x, op_name="sequence_mask")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """paddle.nn.functional.zeropad2d: [left, right, top, bottom]."""
    pl_, pr, pt, pb = (int(v) for v in padding)

    def fn(v):
        if data_format == "NCHW":
            cfg = ((0, 0), (0, 0), (pt, pb), (pl_, pr))
        else:  # NHWC
            cfg = ((0, 0), (pt, pb), (pl_, pr), (0, 0))
        return jnp.pad(v, cfg)

    return apply(fn, ensure_tensor(x), op_name="zeropad2d")


def gather_tree(ids, parents, name=None):
    """paddle.nn.functional.gather_tree: back-trace beam-search parent
    pointers. ids/parents: (T, B, W) → full sequences (T, B, W)."""
    def fn(idv, par):
        t = idv.shape[0]

        def body(carry, xs):
            beam = carry  # (B, W) beam index selected at step t+1
            ids_t, par_t = xs
            tok = jnp.take_along_axis(ids_t, beam, axis=1)
            prev = jnp.take_along_axis(par_t, beam, axis=1)
            return prev.astype(beam.dtype), tok

        init = jnp.broadcast_to(
            jnp.arange(idv.shape[2], dtype=idv.dtype), idv.shape[1:])
        _, toks = jax.lax.scan(
            body, init, (idv[::-1], par[::-1]))
        return toks[::-1]

    return apply(fn, ensure_tensor(ids), ensure_tensor(parents),
                 op_name="gather_tree")


__all__ += ["pairwise_distance", "sequence_mask", "zeropad2d", "gather_tree"]
