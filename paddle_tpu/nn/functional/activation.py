"""Activation functionals (reference: python/paddle/nn/functional/activation.py
— unverified, SURVEY.md §0)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helpers import Tensor, apply, ensure_tensor


def _act(jfn, name):
    def op(x, name=None):
        return apply(jfn, ensure_tensor(x), op_name=name)

    op.__name__ = name
    return op


relu = _act(jax.nn.relu, "relu")
relu6 = _act(jax.nn.relu6, "relu6")
sigmoid = _act(jax.nn.sigmoid, "sigmoid")
tanh = _act(jnp.tanh, "tanh")
silu = _act(jax.nn.silu, "silu")
softsign = _act(jax.nn.soft_sign, "softsign")
tanhshrink = _act(lambda x: x - jnp.tanh(x), "tanhshrink")
mish = _act(lambda x: x * jnp.tanh(jax.nn.softplus(x)), "mish")
log_sigmoid = _act(jax.nn.log_sigmoid, "log_sigmoid")


def relu_(x):
    return x._rebind(relu(x))


def gelu(x, approximate=False, name=None):
    return apply(
        lambda v: jax.nn.gelu(v, approximate=approximate),
        ensure_tensor(x),
        op_name="gelu",
    )


def swish(x, name=None):
    return silu(x)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import to_jax_dtype

    jdt = to_jax_dtype(dtype) if dtype else None

    def fn(v):
        if jdt is not None:
            v = v.astype(jdt)
        return jax.nn.softmax(v, axis=axis)

    return apply(fn, ensure_tensor(x), op_name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._rebind(softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import to_jax_dtype

    jdt = to_jax_dtype(dtype) if dtype else None

    def fn(v):
        if jdt is not None:
            v = v.astype(jdt)
        return jax.nn.log_softmax(v, axis=axis)

    return apply(fn, ensure_tensor(x), op_name="log_softmax")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(
        lambda v: jax.nn.leaky_relu(v, negative_slope),
        ensure_tensor(x),
        op_name="leaky_relu",
    )


def elu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.elu(v, alpha), ensure_tensor(x), op_name="elu")


def elu_(x, alpha=1.0, name=None):
    return x._rebind(elu(x, alpha))


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return apply(
        lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
        ensure_tensor(x),
        op_name="selu",
    )


def celu(x, alpha=1.0, name=None):
    return apply(
        lambda v: jax.nn.celu(v, alpha), ensure_tensor(x), op_name="celu"
    )


def hardshrink(x, threshold=0.5, name=None):
    return apply(
        lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0),
        ensure_tensor(x),
        op_name="hardshrink",
    )


def softshrink(x, threshold=0.5, name=None):
    return apply(
        lambda v: jnp.where(
            v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, 0.0)
        ),
        ensure_tensor(x),
        op_name="softshrink",
    )


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(
        lambda v: jnp.clip(slope * v + offset, 0.0, 1.0),
        ensure_tensor(x),
        op_name="hardsigmoid",
    )


def hardswish(x, name=None):
    return apply(
        lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0,
        ensure_tensor(x),
        op_name="hardswish",
    )


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(
        lambda v: jnp.clip(v, min, max), ensure_tensor(x), op_name="hardtanh"
    )


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        lambda v: jnp.where(
            beta * v > threshold, v, (1.0 / beta) * jnp.log1p(jnp.exp(beta * v))
        ),
        ensure_tensor(x),
        op_name="softplus",
    )


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(
        lambda v: jnp.where(v > threshold, v, value),
        ensure_tensor(x),
        op_name="thresholded_relu",
    )


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def fn(v, w):
        if w.size > 1:
            ch_axis = 1 if data_format == "NCHW" and v.ndim > 1 else v.ndim - 1
            shape = [1] * v.ndim
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(v >= 0, v, w * v)

    return apply(fn, x, weight, op_name="prelu")


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    x = ensure_tensor(x)
    if training:
        from ...core.random import next_key

        key = next_key()
        return apply(
            lambda v: jnp.where(
                v >= 0,
                v,
                v * jax.random.uniform(key, v.shape, v.dtype, lower, upper),
            ),
            x,
            op_name="rrelu",
        )
    mid = (lower + upper) / 2.0
    return apply(lambda v: jnp.where(v >= 0, v, mid * v), x, op_name="rrelu")


def glu(x, axis=-1, name=None):
    return apply(lambda v: jax.nn.glu(v, axis=axis), ensure_tensor(x), op_name="glu")


def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1 :]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)

    return apply(fn, x, op_name="maxout")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...tensor.random import gumbel_softmax as _gs

    return _gs(x, temperature, hard, axis)


__all__ = [
    n
    for n, v in list(globals().items())
    if not n.startswith("_")
    and callable(v)
    and getattr(v, "__module__", None) == __name__
]
